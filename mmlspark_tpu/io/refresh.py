"""Drift-triggered incremental refresh — the control plane that closes
the online-learning loop (ISSUE 18, ROADMAP item 3).

Before this module every block existed but nothing composed them: a
sustained ``feature_drift`` burn could only roll a canary *back* — it
never *fixed* the model.  :class:`RefreshController` is the missing
state machine::

    IDLE ──burn×hysteresis──▶ TRIGGERED ──dataset durable──▶ FITTING
      ▲                                                         │
      │                                      fit ok → publish   │
      │  promoted / rolled_back                 candidate       ▼
      └────────── CANARY ◀──start_canary── CANDIDATE ◀──────────┘
                                    (fit failure: bounded backoff
                                     retry ×N, then GAVE_UP)

* **Trigger** — subscribes to the :class:`~mmlspark_tpu.core.slo.
  SLOMonitor`'s ``feature_drift`` / ``prediction_drift`` burn verdicts;
  ``hysteresis_evals`` consecutive breached polls are required to arm,
  and a ``cooldown_s`` window after every completed episode (promoted,
  rolled back, or given up) absorbs drift storms — with the
  single-state-machine design this also enforces
  max-concurrent-refresh = 1 by construction.
* **Fit** — continued training from the streaming ingest's retained
  rows (:func:`mmlspark_tpu.gbdt.engine.train_incremental` with
  ``init_model`` = the registry's ACTIVE version).  The training view
  is first made durable (``flush()`` + one atomic dataset file) and the
  fit runs under ``checkpoint_dir``, so a trainer SIGKILLed mid-boost
  resumes from the last durable chunk on the SAME bytes — bit-identical
  to an unkilled fit.  Fit failures retry with doubling bounded
  backoff; exhausting ``max_retries`` journals + flight-records a
  ``GAVE_UP`` terminal (a human decision point, never a retrain storm).
* **Hand-off** — the merged forest is published as a registry
  candidate (stamped with the refresh episode) and handed to
  :meth:`~mmlspark_tpu.io.rollout.RolloutController.start_canary`; the
  rollout gate owns promote/rollback, and the controller watches the
  REGISTRY entry state (the durable source of truth) to close the
  episode.
* **Kill-anywhere recovery** — every transition commits a state file
  (tmp+fsync+rename, the registry's manifest discipline) BEFORE acting
  on it, and every action is idempotent against its own re-execution:
  a re-run TRIGGERED re-snapshots the dataset; a re-run FITTING first
  *adopts* an already-published candidate for its episode from the
  registry (so publish is exactly-once even if the process dies between
  publish and commit); a re-run CANDIDATE re-issues ``start_canary``
  against the rebuilt rollout.  docs/online-learning.md §Recovery
  matrix enumerates every kill point.

Telemetry: StageStats under ``ns="refresh"`` plus the
``mmlspark_tpu_refresh_*`` families (docs/observability.md); every
transition journals a ``refresh_*`` event carrying the episode id, so
one merged journal trace reconstructs the whole
trigger→fit→canary→promote chain (the chaos drill's evidence).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.profiling import StageStats
from ..core.slo import SLOMonitor
from ..core.telemetry import PREFIX, _fmt, _labels, get_journal, \
    get_registry, record_flight
from ..gbdt.engine import TrainParams, train_incremental
from ..gbdt.objectives import Objective, RegressionL2
from .ingest import IngestBuffer, _savez_atomic
from .registry import ModelRegistry, RegistryError, _atomic_write
from .rollout import RolloutController

log = logging.getLogger(__name__)

__all__ = ["RefreshConfig", "RefreshController", "RefreshError"]

_STATE_FILE = "refresh_state.json"
_DATASET_FMT = "dataset_%04d.npz"
_CKPT_FMT = "ckpt_%04d"
_FORMAT = 1

REFRESH_NS = "refresh"

#: machine states (docs/online-learning.md §State machine)
STATES = ("idle", "triggered", "fitting", "candidate", "canary",
          "gave_up")


class RefreshError(RuntimeError):
    """Refresh contract violation (unknown durable state, incompatible
    directory)."""


@dataclasses.dataclass
class RefreshConfig:
    """Knobs (docs/online-learning.md §Knobs)."""
    #: SLO objective names whose breach arms the trigger
    trigger_objectives: tuple = ("feature_drift", "prediction_drift")
    #: consecutive breached polls required to arm (debounce)
    hysteresis_evals: int = 2
    #: quiet period after every completed episode
    cooldown_s: float = 60.0
    #: fit attempts per episode before GAVE_UP
    max_retries: int = 3
    #: base retry backoff (doubles per attempt, capped)
    backoff_s: float = 1.0
    backoff_max_s: float = 30.0
    #: refuse to fit on fewer retained rows (stay TRIGGERED, waiting)
    min_fit_rows: int = 256
    #: trees added per refresh fit
    num_iterations: int = 20
    #: chunk boundary for the fit's durable checkpoints
    checkpoint_chunk: int = 8


class RefreshController:
    """The drift → retrain → canary state machine.

    ``root`` is the controller's durable directory (state file,
    episode datasets, fit checkpoints).  Reopening a directory whose
    previous owner was SIGKILLed resumes from the committed state.
    Drive it with :meth:`poll` (each call performs at most one
    state-transition's work; ``now`` injects a fake clock for tests)
    or :meth:`start`/:meth:`stop` for a background thread.
    """

    def __init__(self, root: str, *, registry: ModelRegistry,
                 rollout: Optional[RolloutController],
                 ingest: IngestBuffer,
                 monitor: Optional[SLOMonitor] = None,
                 config: Optional[RefreshConfig] = None,
                 objective: Optional[Objective] = None,
                 train_params: Optional[TrainParams] = None,
                 stats: Optional[StageStats] = None,
                 own_sampling: bool = True,
                 register: bool = True):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.registry = registry
        self.rollout = rollout
        self.ingest = ingest
        self.monitor = monitor
        self.cfg = config or RefreshConfig()
        self.objective = objective or RegressionL2()
        base = train_params or TrainParams(
            num_leaves=15, learning_rate=0.1, min_data_in_leaf=5,
            parallelism="serial", verbosity=0)
        self._params = dataclasses.replace(
            base, num_iterations=self.cfg.num_iterations,
            checkpoint_chunk=self.cfg.checkpoint_chunk)
        self.stats = stats or StageStats()
        self._own_sampling = own_sampling
        self._journal = get_journal()
        self._lock = threading.RLock()
        self._streak = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: chaos/test seam: callbacks handed to the incremental fit
        #: (the drill injects its mid-boost SIGKILL here, the exact
        #: analog of the rollout's ``canary_wrap``)
        self.fit_callbacks: Optional[List] = None
        for k in ("triggers", "fits", "fit_failures", "retries",
                  "candidates", "canaries", "promotions", "rollbacks",
                  "gave_up", "recoveries", "starved"):
            self.stats.incr(k, 0)
        # durable state
        self.state = "idle"
        self.episode = 0
        self.attempt = 0
        self.candidate_version: Optional[int] = None
        self.cooldown_until = 0.0
        self.backoff_until = 0.0
        self._load_or_init()
        if register:
            reg = get_registry()
            reg.register(REFRESH_NS, self.stats)
            reg.register_exposition(REFRESH_NS, self.render_prometheus)
        self._registered = register

    # -- durable state -------------------------------------------------------

    def _state_path(self) -> str:
        return os.path.join(self.root, _STATE_FILE)

    def _commit(self) -> None:
        doc = {"format": _FORMAT, "state": self.state,
               "episode": self.episode, "attempt": self.attempt,
               "candidate_version": self.candidate_version,
               "cooldown_until": self.cooldown_until,
               "backoff_until": self.backoff_until}
        _atomic_write(self._state_path(),
                      json.dumps(doc, indent=1,
                                 sort_keys=True).encode("utf-8"))

    def _load_or_init(self) -> None:
        path = self._state_path()
        if not os.path.exists(path):
            self._commit()
            return
        try:
            with open(path, "rb") as fh:
                doc = json.loads(fh.read().decode("utf-8"))
        except (OSError, ValueError) as e:
            raise RefreshError(
                f"unreadable refresh state {path}: {e}") from e
        if doc.get("format") != _FORMAT:
            raise RefreshError(
                f"refresh state format {doc.get('format')!r} not "
                f"supported (want {_FORMAT})")
        if doc["state"] not in STATES:
            raise RefreshError(
                f"refresh state {doc['state']!r} unknown")
        self.state = doc["state"]
        self.episode = int(doc["episode"])
        self.attempt = int(doc["attempt"])
        cv = doc.get("candidate_version")
        self.candidate_version = None if cv is None else int(cv)
        self.cooldown_until = float(doc.get("cooldown_until", 0.0))
        self.backoff_until = float(doc.get("backoff_until", 0.0))
        if self.state != "idle":
            # a previous owner died mid-episode; the next poll()
            # resumes exactly where the committed state says
            self.stats.incr("recoveries")
            self._journal.emit("refresh_recovered", state=self.state,
                              episode=self.episode,
                              attempt=self.attempt)

    def _transition(self, state: str, event: str, **fields) -> None:
        self.state = state
        self._commit()
        self._journal.emit(event, episode=self.episode,
                          state=state, **fields)

    # -- paths ---------------------------------------------------------------

    def dataset_path(self, episode: Optional[int] = None) -> str:
        ep = self.episode if episode is None else episode
        return os.path.join(self.root, _DATASET_FMT % ep)

    def checkpoint_dir(self, episode: Optional[int] = None) -> str:
        ep = self.episode if episode is None else episode
        return os.path.join(self.root, _CKPT_FMT % ep)

    # -- the machine ---------------------------------------------------------

    def poll(self, now: Optional[float] = None) -> str:
        """Advance the machine by at most one transition's work.
        Returns a status string (the state after the poll, or a
        wait-reason like ``"cooldown"`` / ``"backoff"`` /
        ``"starved"``)."""
        t = time.time() if now is None else float(now)
        with self._lock:
            self.stats.set_gauge(
                "cooldown_remaining_s",
                max(0.0, self.cooldown_until - t))
            if self.state == "gave_up":
                return "gave_up"
            if self.state == "idle":
                return self._poll_idle(t)
            if self.state == "triggered":
                return self._poll_triggered(t)
            if self.state == "fitting":
                return self._poll_fitting(t)
            if self.state == "candidate":
                return self._poll_candidate(t)
            if self.state == "canary":
                return self._poll_canary(t)
            raise RefreshError(f"unreachable state {self.state!r}")

    def _breaching(self, t: float) -> List[str]:
        if self.monitor is None:
            return []
        if self._own_sampling:
            self.monitor.sample(now=t)
        verdicts = self.monitor.evaluate()
        return sorted(
            name for name in self.cfg.trigger_objectives
            if verdicts.get(name, {}).get("breach"))

    def _poll_idle(self, t: float) -> str:
        if t < self.cooldown_until:
            self._streak = 0
            self.stats.set_gauge("breach_streak", 0)
            return "cooldown"
        burning = self._breaching(t)
        self._streak = self._streak + 1 if burning else 0
        self.stats.set_gauge("breach_streak", self._streak)
        if self._streak < self.cfg.hysteresis_evals:
            return "idle"
        self._streak = 0
        self.episode += 1
        self.attempt = 0
        self.candidate_version = None
        self.stats.incr("triggers")
        self.stats.set_gauge("breach_streak", 0)
        self._transition("triggered", "refresh_triggered",
                         objectives=",".join(burning))
        return "triggered"

    def _poll_triggered(self, t: float) -> str:
        self.ingest.flush()
        bins, labels = self.ingest.training_view()
        if len(bins) < self.cfg.min_fit_rows:
            self.stats.incr("starved")
            return "starved"
        # the fit dataset becomes ONE durable file: a killed-and-
        # resumed fit must see the identical bytes or the checkpoint
        # fingerprint would (correctly) refuse to resume
        _savez_atomic(self.dataset_path(), bins=bins, labels=labels,
                      episode=np.int64(self.episode))
        self._transition("fitting", "refresh_dataset",
                         rows=int(len(bins)))
        return "fitting"

    def _adopt_candidate_locked(self) -> Optional[int]:
        """Exactly-once publish: if a previous owner died between
        publish and commit, the registry already holds this episode's
        candidate — adopt it instead of re-fitting."""
        for v, e in sorted(self.registry.entries().items()):
            meta = e.get("meta") or {}
            if meta.get("refresh_episode") == self.episode:
                return int(v)
        return None

    def _poll_fitting(self, t: float) -> str:
        if t < self.backoff_until:
            return "backoff"
        adopted = self._adopt_candidate_locked()
        if adopted is not None:
            self.candidate_version = adopted
            self.stats.incr("candidates")
            self._transition("candidate", "refresh_candidate",
                             version=adopted, adopted=True)
            return "candidate"
        active = self.registry.active_version()
        if active is None:
            raise RefreshError(
                "refresh needs an active registry version as the "
                "init model")
        try:
            with np.load(self.dataset_path()) as ds:
                bins = np.ascontiguousarray(ds["bins"], np.uint8)
                labels = np.asarray(ds["labels"], np.float64)
            init = self.registry.load(active)
            params = dataclasses.replace(
                self._params, checkpoint_dir=self.checkpoint_dir())
            self.stats.incr("fits")
            self._journal.emit("refresh_fit_begin",
                              episode=self.episode,
                              attempt=self.attempt,
                              init_version=active,
                              rows=int(len(bins)))
            with self.stats.time("fit"):
                merged = train_incremental(
                    bins, labels, self.ingest.mapper,
                    init_booster=init, objective=self.objective,
                    params=params, callbacks=self.fit_callbacks)
            version = self.registry.publish(
                merged, meta={"refresh_episode": self.episode,
                              "init_version": int(active),
                              "attempt": self.attempt})
        except Exception as e:  # noqa: BLE001 - bounded retry wall
            self.stats.incr("fit_failures")
            self.attempt += 1
            if self.attempt > self.cfg.max_retries:
                self.stats.incr("gave_up")
                self._transition("gave_up", "refresh_gave_up",
                                 attempts=self.attempt,
                                 error=type(e).__name__)
                record_flight("refresh_gave_up",
                              {"episode": self.episode,
                               "attempts": self.attempt,
                               "error": repr(e)})
                log.exception(
                    "refresh episode %d gave up after %d attempts",
                    self.episode, self.attempt)
                return "gave_up"
            back = min(self.cfg.backoff_s * 2 ** (self.attempt - 1),
                       self.cfg.backoff_max_s)
            self.backoff_until = t + back
            self.stats.incr("retries")
            self._commit()
            self._journal.emit("refresh_retry", episode=self.episode,
                              attempt=self.attempt,
                              backoff_s=round(back, 3),
                              error=type(e).__name__)
            log.warning("refresh fit attempt %d failed (%s); retrying "
                        "in %.1fs", self.attempt, e, back)
            return "backoff"
        self.candidate_version = version
        self.stats.incr("candidates")
        self._transition("candidate", "refresh_candidate",
                         version=version, trees=len(merged.trees))
        return "candidate"

    def _poll_candidate(self, t: float) -> str:
        v = self.candidate_version
        state = self.registry.entry(v)["promoted_state"]
        if state in ("active", "retired"):
            return self._finish(t, "promoted")
        if state in ("rolled_back", "quarantined"):
            return self._finish(t, "rolled_back")
        if self.rollout is None:
            return "candidate"      # waiting for a rollout to attach
        info = self.rollout.model_info()
        arms = {a["arm"]: a for a in info["arms"]}
        if "canary" in arms:
            if arms["canary"].get("version") == v:
                self.stats.incr("canaries")
                self._transition("canary", "refresh_canary", version=v)
                return "canary"
            return "blocked"        # someone else's canary in flight
        try:
            self.rollout.start_canary(v)
        except RegistryError as e:
            self._journal.emit("refresh_canary_blocked",
                              episode=self.episode, version=v,
                              error=str(e))
            return "blocked"
        self.stats.incr("canaries")
        self._transition("canary", "refresh_canary", version=v)
        return "canary"

    def _poll_canary(self, t: float) -> str:
        # the registry entry state is the durable verdict — the gate
        # (or a human) commits promote/rollback there
        state = self.registry.entry(
            self.candidate_version)["promoted_state"]
        if state in ("active", "retired"):
            return self._finish(t, "promoted")
        if state in ("rolled_back", "quarantined"):
            return self._finish(t, "rolled_back")
        return "canary"

    def _finish(self, t: float, outcome: str) -> str:
        self.stats.incr(
            "promotions" if outcome == "promoted" else "rollbacks")
        self.cooldown_until = t + self.cfg.cooldown_s
        self.backoff_until = 0.0
        version = self.candidate_version
        self.candidate_version = None
        self.attempt = 0
        self._transition("idle", "refresh_" + outcome,
                         version=version,
                         cooldown_s=self.cfg.cooldown_s)
        return outcome

    def reset(self, now: Optional[float] = None) -> None:
        """Clear a GAVE_UP terminal (the human acknowledged) back to
        IDLE under a fresh cooldown."""
        t = time.time() if now is None else float(now)
        with self._lock:
            if self.state != "gave_up":
                raise RefreshError(
                    f"reset only applies to gave_up, state is "
                    f"{self.state!r}")
            self.cooldown_until = t + self.cfg.cooldown_s
            self.attempt = 0
            self.candidate_version = None
            self._transition("idle", "refresh_reset")

    # -- background drive ----------------------------------------------------

    def start(self, interval_s: float = 1.0) -> "RefreshController":
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll()
                except Exception:   # noqa: BLE001 - keep the loop up
                    log.exception("refresh poll failed")

        self._thread = threading.Thread(
            target=loop, name="refresh-controller", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        self.stop()
        if self._registered:
            reg = get_registry()
            reg.unregister(REFRESH_NS)
            reg.unregister_exposition(REFRESH_NS)
            self._registered = False

    # -- exposition ----------------------------------------------------------

    def render_prometheus(self, prefix: str = PREFIX) -> str:
        """The ``mmlspark_tpu_refresh_*`` families
        (docs/observability.md §Metric families)."""
        snap = self.stats.snapshot()
        c, g = snap["counters"], snap["gauges"]
        with self._lock:
            state, episode = self.state, self.episode
        lines: List[str] = []

        def fam(suffix: str, typ: str, help_: str) -> str:
            name = f"{prefix}_refresh_{suffix}"
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {typ}")
            return name

        n = fam("state", "gauge",
                "1 for the refresh state machine's current state, 0 "
                "for the others.")
        for s in STATES:
            lines.append(f'{n}{_labels({"state": s})} '
                         f'{1 if s == state else 0}')
        n = fam("episode", "gauge",
                "Monotonic refresh episode counter.")
        lines.append(f"{n} {episode}")
        n = fam("transitions_total", "counter",
                "Refresh lifecycle events, by event.")
        for ev, key in (("triggered", "triggers"),
                        ("fit", "fits"),
                        ("fit_failed", "fit_failures"),
                        ("retry", "retries"),
                        ("candidate", "candidates"),
                        ("canary", "canaries"),
                        ("promoted", "promotions"),
                        ("rolled_back", "rollbacks"),
                        ("gave_up", "gave_up"),
                        ("recovered", "recoveries"),
                        ("starved", "starved")):
            lines.append(f'{n}{_labels({"event": ev})} '
                         f'{c.get(key, 0)}')
        n = fam("breach_streak", "gauge",
                "Consecutive breached trigger polls (arms at the "
                "hysteresis threshold).")
        lines.append(f"{n} {_fmt(g.get('breach_streak', 0))}")
        n = fam("cooldown_seconds", "gauge",
                "Seconds of post-episode cooldown remaining.")
        lines.append(
            f"{n} {_fmt(g.get('cooldown_remaining_s', 0))}")
        return "\n".join(lines) + "\n"
