"""Face API transformers.

Reference: cognitive/Face.scala (expected path, UNVERIFIED — SURVEY.md
§2.1).
"""

from ..core.params import Param, TypeConverters
from .base import CognitiveServiceBase


class DetectFace(CognitiveServiceBase):
    """Face detection; row value is an image URL or payload dict."""
    _path = "/face/v1.0/detect"

    returnFaceId = Param("returnFaceId", "Return face ids", default=True,
                         typeConverter=TypeConverters.toBool)
    returnFaceLandmarks = Param("returnFaceLandmarks",
                                "Return landmarks", default=False,
                                typeConverter=TypeConverters.toBool)
    returnFaceAttributes = Param("returnFaceAttributes",
                                 "Attribute list", default=[],
                                 typeConverter=TypeConverters.toListString)

    def _wrap(self, value):
        if isinstance(value, dict):
            return value
        return {"url": str(value)}

    def _query(self):
        q = {"returnFaceId": str(self.getReturnFaceId()).lower(),
             "returnFaceLandmarks":
                 str(self.getReturnFaceLandmarks()).lower()}
        attrs = self.getReturnFaceAttributes()
        if attrs:
            q["returnFaceAttributes"] = ",".join(attrs)
        return q


class FindSimilarFace(CognitiveServiceBase):
    """Similar-face search; row value is the request payload
    (faceId + faceIds/faceListId)."""
    _path = "/face/v1.0/findsimilars"


class GroupFaces(CognitiveServiceBase):
    """Groups face ids by similarity; row value holds {"faceIds": [...]}."""
    _path = "/face/v1.0/group"


class IdentifyFaces(CognitiveServiceBase):
    """Identifies faces against a person group; row value is the payload."""
    _path = "/face/v1.0/identify"


class VerifyFaces(CognitiveServiceBase):
    """Verifies two faces belong to the same person; row value payload."""
    _path = "/face/v1.0/verify"
