"""Computer Vision transformers.

Reference: cognitive/ComputerVision.scala (expected path, UNVERIFIED —
SURVEY.md §2.1).  Row values are image URLs (wrapped as {"url": ...}) or
ready payload dicts.
"""

from ..core.params import Param, TypeConverters
from .base import CognitiveServiceBase


class _ImageServiceBase(CognitiveServiceBase):
    __abstractstage__ = True

    def _wrap(self, value):
        if isinstance(value, dict):
            return value
        return {"url": str(value)}


class AnalyzeImage(_ImageServiceBase):
    """Full image analysis (categories/tags/description/faces/color)."""
    _path = "/vision/v3.2/analyze"

    visualFeatures = Param("visualFeatures",
                           "Comma-joined feature list",
                           default=["Categories"],
                           typeConverter=TypeConverters.toListString)

    def _query(self):
        return {"visualFeatures": ",".join(self.getVisualFeatures())}


class DescribeImage(_ImageServiceBase):
    """Natural-language image captions."""
    _path = "/vision/v3.2/describe"

    maxCandidates = Param("maxCandidates", "Caption candidates", default=1,
                          typeConverter=TypeConverters.toInt)

    def _query(self):
        return {"maxCandidates": str(self.getMaxCandidates())}


class OCR(_ImageServiceBase):
    """Printed-text OCR."""
    _path = "/vision/v3.2/ocr"

    detectOrientation = Param("detectOrientation",
                              "Detect text orientation", default=True,
                              typeConverter=TypeConverters.toBool)

    def _query(self):
        return {"detectOrientation":
                str(self.getDetectOrientation()).lower()}


class RecognizeText(_ImageServiceBase):
    """Async text recognition (Read API submit call)."""
    _path = "/vision/v3.2/read/analyze"

    mode = Param("mode", "Printed or Handwritten", default="Printed",
                 typeConverter=TypeConverters.toString)

    def _query(self):
        return {"mode": self.getMode()}


class TagImage(_ImageServiceBase):
    """Content tags with confidence."""
    _path = "/vision/v3.2/tag"


class GenerateThumbnails(_ImageServiceBase):
    """Smart-cropped thumbnails."""
    _path = "/vision/v3.2/generateThumbnail"

    width = Param("width", "Thumbnail width", default=64,
                  typeConverter=TypeConverters.toInt)
    height = Param("height", "Thumbnail height", default=64,
                   typeConverter=TypeConverters.toInt)
    smartCropping = Param("smartCropping", "Smart cropping", default=True,
                          typeConverter=TypeConverters.toBool)

    def _query(self):
        return {"width": str(self.getWidth()),
                "height": str(self.getHeight()),
                "smartCropping": str(self.getSmartCropping()).lower()}


class RecognizeDomainSpecificContent(_ImageServiceBase):
    """Domain-model analysis (celebrities/landmarks)."""

    model = Param("model", "Domain model name", default="celebrities",
                  typeConverter=TypeConverters.toString)

    @property
    def _path(self):  # path depends on the model param
        return f"/vision/v3.2/models/{self._peek('model', 'celebrities')}/analyze"
