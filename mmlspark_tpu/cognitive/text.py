"""Text Analytics transformers.

Reference: cognitive/TextAnalytics.scala (expected path, UNVERIFIED —
SURVEY.md §2.1).
"""

from .base import DocumentServiceBase


class TextSentiment(DocumentServiceBase):
    """Sentiment scoring per document."""
    _path = "/text/analytics/v3.0/sentiment"


class LanguageDetector(DocumentServiceBase):
    """Language identification per document."""
    _path = "/text/analytics/v3.0/languages"

    def _wrap(self, value):
        texts = value if isinstance(value, (list, tuple)) else [value]
        return {"documents": [{"id": str(i), "text": str(t)}
                              for i, t in enumerate(texts)]}


class EntityDetector(DocumentServiceBase):
    """Linked-entity recognition."""
    _path = "/text/analytics/v3.0/entities/linking"


class NER(DocumentServiceBase):
    """Named-entity recognition (general)."""
    _path = "/text/analytics/v3.0/entities/recognition/general"


class KeyPhraseExtractor(DocumentServiceBase):
    """Key-phrase extraction."""
    _path = "/text/analytics/v3.0/keyPhrases"
