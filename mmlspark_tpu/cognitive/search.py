"""Bing image search + Azure Search sink.

Reference: cognitive/BingImageSearch.scala, cognitive/AzureSearch.scala
(expected paths, UNVERIFIED — SURVEY.md §2.1).
"""

from __future__ import annotations

import urllib.parse
from typing import Any

from ..core.params import Param, TypeConverters
from ..io.http import HTTPRequestData
from .base import CognitiveServiceBase


class BingImageSearch(CognitiveServiceBase):
    """Image search: row value is the query string (GET with q= param)."""

    count = Param("count", "Results per query", default=10,
                  typeConverter=TypeConverters.toInt)
    offset = Param("offset", "Result offset", default=0,
                   typeConverter=TypeConverters.toInt)
    imageType = Param("imageType", "Filter: Photo/Clipart/...", default=None,
                      typeConverter=TypeConverters.toString)

    def getUrl(self) -> str:
        url = self._peek("url")
        if url:
            return url
        return "https://api.bing.microsoft.com/v7.0/images/search"

    def _prepare(self, payload: Any) -> HTTPRequestData:
        q = urllib.parse.quote(str(payload))
        url = (f"{self.getUrl()}?q={q}&count={self.getCount()}"
               f"&offset={self.getOffset()}")
        img_type = self._peek("imageType")
        if img_type:
            url += f"&imageType={img_type}"
        headers = {}
        key = self._peek("subscriptionKey")
        if key:
            headers["Ocp-Apim-Subscription-Key"] = key
        return HTTPRequestData(url, "GET", headers, None)

    @staticmethod
    def downloadFromUrls(table, urlCol: str, bytesCol: str = "bytes",
                         concurrency: int = 8, timeout: float = 30.0):
        """Fetch image bytes for a URL column (reference helper of the same
        name)."""
        from ..io.http import HTTPTransformer
        import numpy as np
        t = HTTPTransformer(inputCol=urlCol, outputCol="_resp",
                            concurrency=concurrency,
                            timeout=timeout).transform(table)
        resp = t["_resp"]
        blobs = np.empty(len(resp), dtype=object)
        for i, r in enumerate(resp):
            blobs[i] = r.body if r.statusCode == 200 else None
        return t.drop("_resp").withColumn(bytesCol, blobs)


class AddDocuments(CognitiveServiceBase):
    """Azure Search document upload; row value is a document dict."""

    serviceName = Param("serviceName", "Search service name", default=None,
                        typeConverter=TypeConverters.toString)
    indexName = Param("indexName", "Target index", default=None,
                      typeConverter=TypeConverters.toString)
    actionCol = Param("actionCol", "Search action", default="@search.action",
                      typeConverter=TypeConverters.toString)

    def getUrl(self) -> str:
        url = self._peek("url")
        if url:
            return url
        svc, idx = self._peek("serviceName"), self._peek("indexName")
        if svc and idx:
            return (f"https://{svc}.search.windows.net/indexes/{idx}"
                    f"/docs/index?api-version=2020-06-30")
        raise ValueError("AddDocuments needs setUrl or serviceName+indexName")

    def _headers(self):
        headers = {"Content-Type": "application/json"}
        key = self._peek("subscriptionKey")
        if key:
            headers["api-key"] = key  # Azure Search uses api-key
        return headers

    def _wrap(self, value: Any) -> Any:
        doc = dict(value)
        doc.setdefault(self.getActionCol(), "upload")
        return {"value": [doc]}


class AzureSearchWriter:
    """Bulk write a table into an Azure Search index via AddDocuments."""

    @staticmethod
    def write(table, url: str = None, subscriptionKey: str = None,
              serviceName: str = None, indexName: str = None,
              docCol: str = "doc", errorCol: str = "error"):
        stage = AddDocuments(inputCol=docCol, outputCol="_indexed",
                             errorCol=errorCol)
        if url:
            stage.setUrl(url)
        if subscriptionKey:
            stage.setSubscriptionKey(subscriptionKey)
        if serviceName:
            stage.setServiceName(serviceName)
        if indexName:
            stage.setIndexName(indexName)
        return stage.transform(table)
