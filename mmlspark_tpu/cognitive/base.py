"""CognitiveServiceBase.

Reference: cognitive/CognitiveServiceBase.scala (expected path, UNVERIFIED
— SURVEY.md §2.1).  Adds subscription-key auth, region-based URL
construction, and per-service payload building on top of
SimpleHTTPTransformer.
"""

from __future__ import annotations

from typing import Any, Dict

from ..core.params import Param, TypeConverters
from ..io.http import HTTPRequestData, JSONInputParser, SimpleHTTPTransformer


class CognitiveServiceBase(SimpleHTTPTransformer):
    """Shared plumbing for all cognitive transformers."""

    __abstractstage__ = True

    #: service URL path, e.g. "/text/analytics/v3.0/sentiment"
    _path = ""

    subscriptionKey = Param("subscriptionKey", "API subscription key",
                            default=None,
                            typeConverter=TypeConverters.toString)
    location = Param("location", "Azure region, e.g. eastus", default=None,
                     typeConverter=TypeConverters.toString)
    url = Param("url", "Full endpoint URL (overrides location)",
                default=None, typeConverter=TypeConverters.toString)
    outputCol = Param("outputCol", "Response column", default="response",
                      typeConverter=TypeConverters.toString)

    def getUrl(self) -> str:
        url = self._peek("url")
        if url:
            return url
        loc = self._peek("location")
        if loc:
            return (f"https://{loc}.api.cognitive.microsoft.com"
                    f"{self._path}")
        raise ValueError(
            f"{type(self).__name__} needs setUrl(...) or setLocation(...)")

    def setLinkedService(self, _service: str):  # Synapse-parity no-op shim
        return self

    def _headers(self) -> Dict[str, str]:
        headers = {"Content-Type": "application/json"}
        key = self._peek("subscriptionKey")
        if key:
            headers["Ocp-Apim-Subscription-Key"] = key
        return headers

    # subclasses override to wrap row payloads into the service envelope
    def _wrap(self, value: Any) -> Any:
        return value

    # subclasses override to surface request options as URL query params
    def _query(self) -> Dict[str, str]:
        return {}

    def _full_url(self) -> str:
        url = self.getUrl()
        query = self._query()
        if query:
            import urllib.parse
            sep = "&" if "?" in url else "?"
            url = url + sep + urllib.parse.urlencode(query)
        return url

    def _prepare(self, payload: Any) -> HTTPRequestData:
        parser = JSONInputParser(self._full_url(), self._headers(),
                                 self.getMethod())
        return parser(self._wrap(payload))


class DocumentServiceBase(CognitiveServiceBase):
    """Text-analytics envelope: value → {"documents": [{id, text, lang}]}.

    A row value may be a plain string (one document) or a list of strings
    (batched documents, ids assigned positionally) — mirroring the
    reference's text-analytics batching.
    """

    __abstractstage__ = True

    language = Param("language", "Default document language", default="en",
                     typeConverter=TypeConverters.toString)

    def _wrap(self, value: Any) -> Any:
        texts = value if isinstance(value, (list, tuple)) else [value]
        lang = self.getLanguage()
        return {"documents": [
            {"id": str(i), "language": lang, "text": str(t)}
            for i, t in enumerate(texts)]}
