"""Speech-to-text transformer.

Reference: cognitive/SpeechToText.scala (expected path, UNVERIFIED —
SURVEY.md §2.1).  Row values are raw audio bytes; the request body is the
audio payload with a WAV content type rather than JSON.
"""

from ..core.params import Param, TypeConverters
from ..io.http import HTTPRequestData
from .base import CognitiveServiceBase


class SpeechToText(CognitiveServiceBase):
    _path = "/speech/recognition/conversation/cognitiveservices/v1"

    audioFormat = Param("audioFormat", "Content type of the audio",
                        default="audio/wav; codecs=audio/pcm; samplerate=16000",
                        typeConverter=TypeConverters.toString)
    speechLanguage = Param("speechLanguage", "Recognition language",
                           default="en-US",
                           typeConverter=TypeConverters.toString)

    def getUrl(self) -> str:
        url = self._peek("url")
        if url:
            return url
        loc = self._peek("location")
        if loc:
            return (f"https://{loc}.stt.speech.microsoft.com{self._path}"
                    f"?language={self.getSpeechLanguage()}")
        raise ValueError("SpeechToText needs setUrl(...) or setLocation(...)")

    def _prepare(self, payload) -> HTTPRequestData:
        body = bytes(payload) if not isinstance(payload, bytes) else payload
        headers = {"Content-Type": self.getAudioFormat()}
        key = self._peek("subscriptionKey")
        if key:
            headers["Ocp-Apim-Subscription-Key"] = key
        return HTTPRequestData(self.getUrl(), "POST", headers, body)
