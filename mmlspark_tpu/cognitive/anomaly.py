"""Anomaly Detector transformers.

Reference: cognitive/AnomalyDetection.scala (expected path, UNVERIFIED —
SURVEY.md §2.1).  Row values are {"series": [{"timestamp", "value"}, ...]}
payloads (or bare lists of points, wrapped with the stage's granularity).
"""

from ..core.params import Param, TypeConverters
from .base import CognitiveServiceBase


class _AnomalyBase(CognitiveServiceBase):
    __abstractstage__ = True

    granularity = Param("granularity",
                        "Series granularity (daily/hourly/minutely...)",
                        default="daily",
                        typeConverter=TypeConverters.toString)
    maxAnomalyRatio = Param("maxAnomalyRatio", "Max anomaly fraction",
                            default=0.25,
                            typeConverter=TypeConverters.toFloat)
    sensitivity = Param("sensitivity", "Detection sensitivity", default=95,
                        typeConverter=TypeConverters.toInt)

    def _wrap(self, value):
        if isinstance(value, dict) and "series" in value:
            return value
        return {"series": list(value),
                "granularity": self.getGranularity(),
                "maxAnomalyRatio": self.getMaxAnomalyRatio(),
                "sensitivity": self.getSensitivity()}


class DetectLastAnomaly(_AnomalyBase):
    """Is the latest point anomalous?"""
    _path = "/anomalydetector/v1.0/timeseries/last/detect"


class DetectAnomalies(_AnomalyBase):
    """Batch detection over the entire series."""
    _path = "/anomalydetector/v1.0/timeseries/entire/detect"


class SimpleDetectAnomalies(_AnomalyBase):
    """Entire-series detection with the simplified grouped API of the
    reference (cognitive/AnomalyDetection.scala SimpleDetectAnomalies)."""
    _path = "/anomalydetector/v1.0/timeseries/entire/detect"
