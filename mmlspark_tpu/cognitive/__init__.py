"""Azure Cognitive Services transformers (reference ``cognitive/``).

Reference: src/main/scala/com/microsoft/ml/spark/cognitive/ (expected
paths, UNVERIFIED — SURVEY.md §2.1): ~30 transformers wrapping Azure REST
APIs, all built on CognitiveServiceBase → SimpleHTTPTransformer.  Same
layering here; each service is a declarative subclass contributing a URL
path and a payload builder.  ``setUrl`` accepts any endpoint, so these run
against mocks/self-hosted gateways without Azure.
"""

from .base import CognitiveServiceBase
from .text import (
    EntityDetector,
    KeyPhraseExtractor,
    LanguageDetector,
    NER,
    TextSentiment,
)
from .vision import (
    AnalyzeImage,
    DescribeImage,
    GenerateThumbnails,
    OCR,
    RecognizeDomainSpecificContent,
    RecognizeText,
    TagImage,
)
from .face import (
    DetectFace,
    FindSimilarFace,
    GroupFaces,
    IdentifyFaces,
    VerifyFaces,
)
from .anomaly import (
    DetectAnomalies,
    DetectLastAnomaly,
    SimpleDetectAnomalies,
)
from .speech import SpeechToText
from .search import AddDocuments, AzureSearchWriter, BingImageSearch

__all__ = [
    "CognitiveServiceBase",
    "TextSentiment", "LanguageDetector", "EntityDetector", "NER",
    "KeyPhraseExtractor",
    "AnalyzeImage", "DescribeImage", "OCR", "RecognizeText", "TagImage",
    "GenerateThumbnails", "RecognizeDomainSpecificContent",
    "DetectFace", "FindSimilarFace", "GroupFaces", "IdentifyFaces",
    "VerifyFaces",
    "DetectLastAnomaly", "DetectAnomalies", "SimpleDetectAnomalies",
    "SpeechToText",
    "BingImageSearch", "AddDocuments", "AzureSearchWriter",
]
