"""Isolation Forest anomaly detection.

Reference: isolationforest/IsolationForest.scala (expected path, UNVERIFIED
— SURVEY.md §2.1), a wrapper around the linkedin/isolation-forest Spark
library.  TPU-native design: trees are grown on host (cheap — random
splits over small subsamples) into fixed-depth arrays; scoring is a jit'd
``vmap`` traversal over (trees × rows), the same array-tree evaluation the
GBDT booster uses.
"""

from .iforest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
