"""Isolation forest: host-grown random trees, device-scored.

Reference: isolationforest/IsolationForest.scala (expected path, UNVERIFIED
— SURVEY.md §2.1).  Trees live in heap-layout arrays (node i → children
2i+1 / 2i+2), so scoring is a depth-bounded ``fori_loop`` gather per tree,
``vmap``ed over trees — no recursion, static shapes, one XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import (HasFeaturesCol, HasPredictionCol, HasSeed, Param,
                           TypeConverters)
from ..core.pipeline import Estimator, Model
from ..core.schema import DataTable, features_matrix
from ..core import serialize

_EULER = 0.5772156649


def _avg_path_len(n) -> float:
    """c(n): average BST unsuccessful-search path length."""
    n = float(n)
    if n <= 1.0:
        return 0.0
    if n == 2.0:
        # exact value; the harmonic approximation below gives ~0.154
        return 1.0
    return 2.0 * (np.log(n - 1.0) + _EULER) - 2.0 * (n - 1.0) / n


@partial(jax.jit, static_argnames=("depth",))
def _path_lengths(X, feat, thr, pathlen, depth: int):
    """X: (N, F); feat/thr/pathlen: (T, M) heap trees → (N, T) path lens."""
    def one_tree(f, t, pl):
        def step(_, node):
            is_leaf = f[node] < 0
            go_left = X[jnp.arange(X.shape[0]),
                        jnp.maximum(f[node], 0)] < t[node]
            child = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
            return jnp.where(is_leaf, node, child)
        node = jax.lax.fori_loop(
            0, depth, step, jnp.zeros(X.shape[0], jnp.int32))
        return pl[node]
    return jax.vmap(one_tree)(feat, thr, pathlen).T


class IsolationForest(HasFeaturesCol, HasPredictionCol, HasSeed, Estimator):
    """Unsupervised anomaly detector (isolationforest/IsolationForest.scala)."""

    numEstimators = Param("numEstimators", "Number of trees", default=100,
                          typeConverter=TypeConverters.toInt)
    maxSamples = Param("maxSamples", "Subsample size per tree", default=256,
                       typeConverter=TypeConverters.toInt)
    maxFeatures = Param("maxFeatures", "Fraction of features per tree",
                        default=1.0, typeConverter=TypeConverters.toFloat)
    contamination = Param("contamination",
                          "Expected anomaly fraction (sets the threshold)",
                          default=0.05, typeConverter=TypeConverters.toFloat)
    scoreCol = Param("scoreCol", "Anomaly score output column",
                     default="outlierScore",
                     typeConverter=TypeConverters.toString)

    def _fit(self, table: DataTable) -> "IsolationForestModel":
        X = np.asarray(features_matrix(table, self.getFeaturesCol()),
                       dtype=np.float32)
        n, F = X.shape
        rng = np.random.default_rng(self.getSeed())
        T = self.getNumEstimators()
        psi = min(self.getMaxSamples(), n)
        depth = max(1, int(np.ceil(np.log2(max(psi, 2)))))
        M = 2 ** (depth + 1) - 1
        n_feats = max(1, int(round(self.getMaxFeatures() * F)))

        feat = np.full((T, M), -1, dtype=np.int32)
        thr = np.zeros((T, M), dtype=np.float32)
        pathlen = np.zeros((T, M), dtype=np.float32)

        for t in range(T):
            sample = X[rng.choice(n, size=psi, replace=False)]
            feat_pool = rng.choice(F, size=n_feats, replace=False)
            # stack of (node, rows, depth)
            stack = [(0, sample, 0)]
            while stack:
                node, rows, d = stack.pop()
                n_rows = len(rows)
                if d >= depth or n_rows <= 1:
                    feat[t, node] = -1
                    pathlen[t, node] = d + _avg_path_len(n_rows)
                    continue
                f = int(rng.choice(feat_pool))
                lo, hi = rows[:, f].min(), rows[:, f].max()
                if lo == hi:
                    feat[t, node] = -1
                    pathlen[t, node] = d + _avg_path_len(n_rows)
                    continue
                s = float(rng.uniform(lo, hi))
                feat[t, node] = f
                thr[t, node] = s
                left_rows = rows[rows[:, f] < s]
                right_rows = rows[rows[:, f] >= s]
                stack.append((2 * node + 1, left_rows, d + 1))
                stack.append((2 * node + 2, right_rows, d + 1))

        # threshold from train scores at the contamination quantile
        lens = np.asarray(_path_lengths(
            jnp.asarray(X), jnp.asarray(feat), jnp.asarray(thr),
            jnp.asarray(pathlen), depth + 1))
        scores = np.power(2.0, -lens.mean(axis=1) / _avg_path_len(psi))
        threshold = float(np.quantile(scores, 1.0 - self.getContamination()))

        model = IsolationForestModel(feat=feat, thr=thr, pathlen=pathlen,
                                     depth=depth, psi=psi,
                                     threshold=threshold)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class IsolationForestModel(HasFeaturesCol, HasPredictionCol, Model):
    scoreCol = IsolationForest.scoreCol

    def __init__(self, feat=None, thr=None, pathlen=None, depth: int = 0,
                 psi: int = 0, threshold: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self._feat, self._thr, self._pathlen = feat, thr, pathlen
        self._depth, self._psi = int(depth), int(psi)
        self._threshold = float(threshold)

    @property
    def threshold(self) -> float:
        return self._threshold

    def _transform(self, table: DataTable) -> DataTable:
        X = np.asarray(features_matrix(table, self.getFeaturesCol()),
                       dtype=np.float32)
        lens = np.asarray(_path_lengths(
            jnp.asarray(X), jnp.asarray(self._feat), jnp.asarray(self._thr),
            jnp.asarray(self._pathlen), self._depth + 1))
        scores = np.power(2.0, -lens.mean(axis=1) / _avg_path_len(self._psi))
        return table.withColumns({
            self.getScoreCol(): scores.astype(np.float64),
            self.getPredictionCol():
                (scores > self._threshold).astype(np.float64),
        })

    def _save_extra(self, path: str) -> None:
        serialize.save_arrays(path, feat=self._feat, thr=self._thr,
                              pathlen=self._pathlen)
        serialize.save_json(path, "meta", {
            "depth": self._depth, "psi": self._psi,
            "threshold": self._threshold})

    def _load_extra(self, path: str) -> None:
        arrays = serialize.load_arrays(path)
        self._feat, self._thr = arrays["feat"], arrays["thr"]
        self._pathlen = arrays["pathlen"]
        meta = serialize.load_json(path, "meta")
        self._depth, self._psi = meta["depth"], meta["psi"]
        self._threshold = meta["threshold"]
