"""ModelDownloader: pretrained-model acquisition and caching.

Analog of the reference's ``downloader/ModelDownloader.scala`` (expected
path, UNVERIFIED; SURVEY.md §2.1), which fetches CNTK models from a public
blob into a local/DBFS cache with hash checks.  This environment has zero
network egress, so the TPU-native version is cache-first: it catalogs known
model schemas, scans standard local cache locations (torch hub, HF hub, an
explicit cache dir), verifies hashes when downloading IS possible, and gives
an actionable error otherwise.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class ModelSchema:
    """Metadata for a known pretrained model (reference downloader/Schema)."""
    name: str
    dataset: str
    input_size: int
    num_outputs: int
    filenames: List[str]  # checkpoint basenames to look for


_KNOWN = {
    "resnet18": ModelSchema("resnet18", "ImageNet", 224, 1000,
                            ["resnet18.pth", "resnet18-f37072fd.pth"]),
    "resnet34": ModelSchema("resnet34", "ImageNet", 224, 1000,
                            ["resnet34.pth", "resnet34-b627a593.pth"]),
    "resnet50": ModelSchema("resnet50", "ImageNet", 224, 1000,
                            ["resnet50.pth", "resnet50-0676ba61.pth",
                             "resnet50-19c8e357.pth"]),
    "resnet101": ModelSchema("resnet101", "ImageNet", 224, 1000,
                             ["resnet101.pth", "resnet101-63fe2227.pth"]),
    "resnet152": ModelSchema("resnet152", "ImageNet", 224, 1000,
                             ["resnet152.pth", "resnet152-394f9c45.pth"]),
}


class ModelDownloader:
    """Cache-first model acquisition (network-free by default)."""

    def __init__(self, local_cache: Optional[str] = None):
        self.local_cache = local_cache or os.environ.get(
            "MMLSPARK_TPU_MODEL_CACHE",
            os.path.expanduser("~/.cache/mmlspark_tpu/models"))

    def list_models(self) -> List[ModelSchema]:
        return list(_KNOWN.values())

    def get_schema(self, name: str) -> ModelSchema:
        if name not in _KNOWN:
            raise KeyError(f"Unknown model {name!r}; known: {sorted(_KNOWN)}")
        return _KNOWN[name]

    def _candidate_dirs(self) -> List[str]:
        dirs = [self.local_cache,
                os.path.expanduser("~/.cache/torch/hub/checkpoints")]
        hf = os.environ.get("HF_HOME",
                            os.path.expanduser("~/.cache/huggingface"))
        dirs.append(os.path.join(hf, "hub"))
        return dirs

    def find_local_checkpoint(self, name: str) -> Optional[str]:
        """Search the cache directories for a known checkpoint file."""
        schema = _KNOWN.get(name)
        if schema is None:
            return None
        for d in self._candidate_dirs():
            if not os.path.isdir(d):
                continue
            for root, _, files in os.walk(d):
                for fn in files:
                    if fn in schema.filenames:
                        return os.path.join(root, fn)
        return None

    def downloadModel(self, name: str) -> str:
        """Return a local checkpoint path, or raise with instructions."""
        path = self.find_local_checkpoint(name)
        if path is not None:
            return path
        schema = self.get_schema(name)
        raise FileNotFoundError(
            f"No local checkpoint for {name!r}. This environment has no "
            f"network egress; place one of {schema.filenames} under "
            f"{self.local_cache} (torchvision-layout state dict).")

    @staticmethod
    def sha256(path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
