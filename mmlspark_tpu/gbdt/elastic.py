"""Elastic multicontroller training: heartbeat leases, retried
rendezvous, gang supervision.

The multicontroller configuration (``tests/test_multicontroller.py``;
SURVEY.md §7 hard part 4) replaces the reference's driver-socket
rendezvous with ``jax.distributed`` — which also inherits its failure
mode: one dead controller wedges every survivor inside a collective
until the runtime's own timeout, minutes later, and the whole boost
restarts from ``initModelPath``.  This module is the training-side
resilience layer (the serving analog shipped in ``io/serving.py``'s
worker supervision):

* :class:`HeartbeatWatchdog` — a lease heartbeat each controller
  advertises and monitors for its peers.  Two wire modes, one policy:
  shared-directory lease FILES (the single-host default), or — with
  ``ElasticConfig.transport_address`` set — lease BEACONS over the
  unified :mod:`mmlspark_tpu.io.transport` to a :class:`HeartbeatHub`
  relay (the multi-host mode; a link blip is absorbed by the session
  resume, so the beacon channel itself cannot fake a dead peer).  A
  stale peer beyond ``straggler_age_s`` is a *straggler* (counted, age
  surfaced as a :class:`~mmlspark_tpu.core.profiling.StageStats`
  gauge); beyond ``lease_timeout_s`` the peer is declared lost and the
  watchdog abandons the wedged process with
  :data:`RESTART_EXIT_CODE` — the mid-fit checkpoint
  (``TrainParams.checkpoint_dir``) makes that abandonment cheap: the
  respawned gang resumes from the last chunk boundary bit-identically.
* :func:`initialize_with_retry` — ``jax.distributed.initialize`` under
  bounded exponential backoff, so transient rendezvous failures
  (``EADDRINUSE`` from a just-released port, a peer that hasn't bound
  yet) retry instead of flaking.
* :func:`supervise` — the gang supervisor loop: spawn a round of
  controller processes, wait, and respawn the whole gang (fresh
  rendezvous port, same checkpoint directory) while any member exits
  nonzero — the reference's executor gang-restart, minus the lost
  work.
* :func:`run_worker` / ``python -m mmlspark_tpu.gbdt.elastic`` — the
  controller entrypoint (promoted from ``tests/multicontroller_worker``):
  form the rendezvous, start the watchdog, run a deterministic sharded
  ``train()`` with ``checkpoint_dir`` live, and dump recovery counters.

``tools/chaos_training.py`` drives all of this under injected faults
(controller SIGKILL, snapshot corruption, heartbeat stalls) and proves
the recovered forest is bit-identical to an uninterrupted run.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..core import telemetry as _tm
from ..core.profiling import StageStats

log = logging.getLogger("mmlspark_tpu.gbdt.elastic")

#: exit code a controller uses to abandon a wedged gang after a peer's
#: lease expired: "respawn me, the checkpoint has my state" — distinct
#: from crash codes so the supervisor can tell recovery from failure
RESTART_EXIT_CODE = 76

_HB_FILE = "hb_p{:03d}"


@dataclass
class ElasticConfig:
    """Knobs for one controller's elastic runtime."""
    heartbeat_dir: str
    process_id: int
    num_processes: int
    #: when set (``host:port``), lease beacons ride the unified
    #: :mod:`mmlspark_tpu.io.transport` session to a
    #: :class:`HeartbeatHub` instead of the shared-filesystem lease
    #: files — the multi-host topology, where no shared directory
    #: exists.  A link blip is absorbed by the transport's resume
    #: (reconnect + replay), so a healthy gang never sees a false
    #: ``peer_lost`` from the beacon channel itself.
    transport_address: str = ""
    #: shared secret for the hub's transport handshake
    transport_token: str = ""
    #: how often each controller touches its lease file
    heartbeat_interval_s: float = 0.25
    #: peer heartbeat age beyond which the peer counts as a STRAGGLER
    #: (counted + gauged, training continues)
    straggler_age_s: float = 1.0
    #: peer heartbeat age beyond which the peer is LOST and the default
    #: handler abandons the process with RESTART_EXIT_CODE
    lease_timeout_s: float = 5.0
    #: grace for a peer's lease file to first appear (process spawn +
    #: jax import happen before the first touch)
    startup_grace_s: float = 60.0
    #: rendezvous retry budget (initialize_with_retry)
    init_retries: int = 4
    init_backoff_s: float = 0.5


class HeartbeatWatchdog:
    """File-lease heartbeat: one writer thread per controller.

    Each tick: run the (chaos-injectable) ``write_hook``, touch this
    process's lease file, then read every peer's file age.  Counters on
    ``stats`` (a :class:`StageStats`):

    * ``heartbeat_stalls`` — transitions of a peer into straggler
      territory (age > ``straggler_age_s``); a slow shard is visible
      long before it is fatal.
    * ``peer_lost`` — lease expiries (age > ``lease_timeout_s``).
    * gauge ``heartbeat_age_ms`` — the worst peer age observed at the
      latest tick.

    ``on_peer_lost(pid, age_s)`` fires once per expired peer; the
    default handler logs and hard-exits with :data:`RESTART_EXIT_CODE`
    (``os._exit``: the survivor is typically wedged inside a collective
    whose peer is gone — no orderly unwind exists, and the chunk
    checkpoint makes the abandonment lossless).
    """

    def __init__(self, cfg: ElasticConfig, *,
                 stats: Optional[StageStats] = None,
                 on_peer_lost: Optional[Callable[[int, float], None]] = None,
                 write_hook: Optional[Callable[[], None]] = None):
        self.cfg = cfg
        self.stats = stats if stats is not None else StageStats()
        self.stats.incr("heartbeat_stalls", 0)
        self.stats.incr("peer_lost", 0)
        self.stats.set_gauge("heartbeat_age_ms", 0.0)
        self._on_peer_lost = on_peer_lost
        self._write_hook = write_hook
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stalled: Dict[int, bool] = {}
        self._lost: Dict[int, bool] = {}
        self._t0 = 0.0
        # last observed mtime per peer + the LOCAL monotonic instant it
        # changed: ages are measured between two local observations, so
        # clock skew between this host and a shared (e.g. NFS) filesystem
        # never inflates a peer's age — comparing local time.time()
        # against a remote mtime would add the skew to every age and a
        # 5s-skewed mount would expire every lease on a healthy gang
        self._peer_mtime: Dict[int, float] = {}
        self._peer_seen: Dict[int, float] = {}
        # transport mode: hub-relayed lease beacons, aged by the LOCAL
        # monotonic receipt time (same skew-immunity argument)
        self._client = None

    def path_for(self, pid: int) -> str:
        return os.path.join(self.cfg.heartbeat_dir, _HB_FILE.format(pid))

    def _touch(self) -> None:
        # the lease carries the CURRENT fit span (liveness itself is
        # observation-based — peers never parse this): a post-mortem can
        # tie "whose lease went stale" to "which fit was running", and a
        # resumed gang's fresh span shows in the lease immediately
        if self._client is not None:
            try:
                from ..io.transport import CH_ELASTIC
                if self._client.closed:
                    # the reconnect budget ran out (hub outage longer
                    # than the backoff ladder): the liveness channel
                    # must not stay dead forever — stand up a fresh
                    # session each tick until the hub answers, so a
                    # recovered hub sees beacons again immediately.
                    # (While the hub is TRULY down, peer ages grow and
                    # the lease policy applies — same as an unreachable
                    # shared directory in file mode; the bug this
                    # guards against is staying dark AFTER recovery.)
                    self._client = self._make_client().connect(
                        retries=0)
                # short send timeout: during a hub outage the queue
                # fills, and a beacon blocked on backpressure must not
                # stall _check_peers (the loop's real job)
                self._client.send(
                    CH_ELASTIC,
                    {"op": "lease", "pid": self.cfg.process_id,
                     "fit": _tm.current_fit_span() or ""},
                    timeout=min(1.0, self.cfg.heartbeat_interval_s))
            except OSError:
                pass   # blip: the transport reconnects and replays
            return
        path = self.path_for(self.cfg.process_id)
        with open(path, "w") as fh:
            fh.write(f"{time.time()} {_tm.current_fit_span() or ''}\n")

    def peer_ages(self) -> Dict[int, float]:
        """Seconds since this watchdog last OBSERVED each peer's lease
        advance (inf = never seen): a peer is as old as the local
        monotonic time since its lease was last observed to move — a
        file mtime change in lease-file mode, a hub-relayed beacon in
        transport mode — never a cross-host clock comparison."""
        now = time.monotonic()
        ages: Dict[int, float] = {}
        for p in range(self.cfg.num_processes):
            if p == self.cfg.process_id:
                continue
            if self._client is not None:
                seen = self._peer_seen.get(p)
                ages[p] = (now - seen) if seen is not None \
                    else float("inf")
                continue
            try:
                mt = os.path.getmtime(self.path_for(p))
            except OSError:
                ages[p] = float("inf")
                continue
            if self._peer_mtime.get(p) != mt:
                self._peer_mtime[p] = mt
                self._peer_seen[p] = now
            ages[p] = now - self._peer_seen[p]
        return ages

    def _on_transport_msg(self, session, channel, obj, deadline_ms):
        from ..io.transport import CH_ELASTIC
        if channel != CH_ELASTIC or obj.get("op") != "lease":
            return
        p = obj.get("pid")
        if isinstance(p, int) and p != self.cfg.process_id:
            self._peer_seen[p] = time.monotonic()

    def _make_client(self):
        from ..io.transport import TransportClient
        return TransportClient(
            self.cfg.transport_address,
            token=self.cfg.transport_token,
            on_message=self._on_transport_msg,
            name=f"heartbeat-p{self.cfg.process_id}")

    def start(self) -> "HeartbeatWatchdog":
        if self.cfg.transport_address:
            self._client = self._make_client()
            self._client.connect()
        else:
            os.makedirs(self.cfg.heartbeat_dir, exist_ok=True)
        # explicit zero at START (matching the incr(_k, 0) seeding of
        # the resilience counters): "no stalls observed yet" is a
        # reading, not a missing key — even if the loop below never
        # completes a tick before the first snapshot
        self.stats.set_gauge("heartbeat_age_ms", 0.0)
        # federate under the process registry so a controller's
        # /metrics (or stats dump) carries the watchdog gauges
        _tm.get_registry().register("elastic", self.stats)
        self._t0 = time.time()
        self._touch()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="elastic-heartbeat")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._client is not None:
            self._client.close()

    def _check_peers(self) -> None:
        cfg = self.cfg
        in_grace = time.time() - self._t0 < cfg.startup_grace_s
        worst = 0.0
        for p, age in self.peer_ages().items():
            if age == float("inf"):
                if in_grace:
                    continue        # peer still booting
                # missing lease file past grace: the gauge must not
                # read 0 ms (healthy) at the very tick a peer is lost
                worst = max(worst, cfg.lease_timeout_s)
            else:
                worst = max(worst, age)
            stalled = age > cfg.straggler_age_s
            if stalled and not self._stalled.get(p):
                self.stats.incr("heartbeat_stalls")
                _tm.get_journal().emit(
                    "peer_stalled", fit=_tm.current_fit_span(), peer=p,
                    age_s=round(age, 3) if age != float("inf")
                    else "inf")
                log.warning("peer %d heartbeat is %.2fs stale "
                            "(straggler threshold %.2fs)", p, age,
                            cfg.straggler_age_s)
            self._stalled[p] = stalled
            if age > cfg.lease_timeout_s and not self._lost.get(p):
                self._lost[p] = True
                self.stats.incr("peer_lost")
                _tm.get_journal().emit(
                    "peer_lost", fit=_tm.current_fit_span(), peer=p,
                    age_s=round(age, 3) if age != float("inf")
                    else "inf")
                self._handle_lost(p, age)
        self.stats.set_gauge("heartbeat_age_ms",
                             round(worst * 1e3, 3))

    def _handle_lost(self, pid: int, age: float) -> None:
        if self._on_peer_lost is not None:
            self._on_peer_lost(pid, age)
            return
        log.error("controller %d lease expired (%.2fs > %.2fs); "
                  "abandoning the gang with RESTART_EXIT_CODE=%d — "
                  "resume comes from the chunk checkpoint", pid, age,
                  self.cfg.lease_timeout_s, RESTART_EXIT_CODE)
        # os._exit runs no cleanup: the flight record is the only
        # artifact this process leaves behind about WHY it abandoned
        _tm.record_flight("peer_lost_abandon",
                          {"peer": pid, "age_s": round(age, 3),
                           "process_id": self.cfg.process_id})
        os._exit(RESTART_EXIT_CODE)

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.heartbeat_interval_s):
            try:
                if self._write_hook is not None:
                    self._write_hook()
                self._touch()
                self._check_peers()
            except Exception:  # noqa: BLE001 - the watchdog must outlive
                # transient filesystem hiccups; a dead watchdog would
                # silently disable the liveness layer
                log.exception("heartbeat tick failed; continuing")


class HeartbeatHub:
    """Lease-beacon relay for the transport heartbeat mode: controllers
    dial in over :mod:`mmlspark_tpu.io.transport` resumable sessions
    and every ``lease`` beacon on the elastic channel fans out to every
    OTHER connected controller.  The hub never interprets leases — it
    is a dumb, authenticated relay (typically run by the gang
    supervisor or controller 0's host), so liveness judgement stays
    where it was: each watchdog ages peers by its own local
    observations.  A controller link blip is absorbed by the session
    resume; only a peer that truly stops beaconing ages out."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 token: str = "", cfg=None):
        from ..io.transport import TransportServer
        self._ts = TransportServer(host, port, token=token, cfg=cfg,
                                   on_message=self._relay,
                                   name="heartbeat-hub")

    @property
    def address(self) -> str:
        h, p = self._ts.address
        return f"{h}:{p}"

    def start(self) -> "HeartbeatHub":
        self._ts.start()
        return self

    def stop(self) -> None:
        self._ts.stop()

    def _relay(self, session, channel, obj, deadline_ms) -> None:
        from ..io.transport import CH_ELASTIC
        if channel != CH_ELASTIC or obj.get("op") != "lease":
            return
        for s in list(self._ts.sessions.values()):
            if s.sid == session.sid or not s.connected:
                continue
            try:
                # near-zero timeout: the relay runs ON the beaconing
                # controller's read pump, so ONE wedged (non-draining)
                # peer must not delay lease delivery to the healthy
                # ones — beacons are periodic and lossy by design,
                # dropping beats blocking
                s.send(CH_ELASTIC, obj, timeout=0.02)
            except OSError:
                pass   # that peer's link is dying; its resume catches up


def initialize_with_retry(coordinator_address: str, num_processes: int,
                          process_id: int, *, retries: int = 4,
                          backoff_s: float = 0.5,
                          sleep: Callable[[float], None] = time.sleep
                          ) -> int:
    """``jax.distributed.initialize`` under bounded exponential backoff.

    A rendezvous can fail transiently: the coordinator's port is in
    TIME_WAIT from a previous gang round (``EADDRINUSE``), or a peer
    hasn't reached its bind yet.  Deterministic parameter errors are
    not retried.  Returns the number of retry attempts consumed."""
    import jax
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
            return attempt
        except (ValueError, TypeError):
            raise                    # bad parameters: retrying can't help
        except Exception as e:  # noqa: BLE001 - runtime rendezvous errors
            last = e
            if attempt >= retries:
                break
            wait = backoff_s * (2 ** attempt)
            log.warning("rendezvous with %s failed (%s: %s); retry "
                        "%d/%d in %.1fs", coordinator_address,
                        type(e).__name__, e, attempt + 1, retries, wait)
            sleep(wait)
    raise RuntimeError(
        f"rendezvous with {coordinator_address} failed after "
        f"{retries + 1} attempts") from last


def enable_cpu_collectives() -> None:
    """Turn on cross-process CPU collectives (gloo) where the installed
    jax still defaults to the stub backend that raises "Multiprocess
    computations aren't implemented on the CPU backend".  Must run
    before backends initialize; harmless no-op on jax versions where
    gloo is already the default or the flag is gone."""
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - option renamed/removed upstream
        pass


def free_port() -> int:
    """An OS-assigned free TCP port (racy by nature — pair with
    :func:`initialize_with_retry` / a fresh-port supervisor round)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def supervise(spawn_round: Callable[[int, int], List],
              *, max_restarts: int = 3, round_timeout_s: float = 600.0,
              verbose: bool = True) -> int:
    """Gang supervisor: run rounds of ``spawn_round(attempt, port) ->
    [Popen, ...]`` until one round exits all-zero.

    Any nonzero exit — a SIGKILLed controller (negative returncode), a
    survivor's :data:`RESTART_EXIT_CODE`, a crash — fails the round and
    the WHOLE gang respawns on a fresh rendezvous port (collective
    state is gang-global; per-member respawn cannot rejoin a live
    ``jax.distributed`` ring).  Lost work is bounded by the chunk
    checkpoint the workers share.  Returns the number of restarts
    consumed; raises after ``max_restarts`` failed rounds."""
    import subprocess
    for attempt in range(max_restarts + 1):
        port = free_port()
        procs = spawn_round(attempt, port)
        deadline = time.time() + round_timeout_s
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(timeout=max(1.0,
                                              deadline - time.time())))
            except subprocess.TimeoutExpired:
                rcs.append(None)
        if any(rc is None for rc in rcs):
            for p in procs:          # a hung round: kill and retry
                if p.poll() is None:
                    p.kill()
            for p in procs:
                p.wait()
        if verbose:
            log.info("gang round %d exited %s", attempt, rcs)
        if all(rc == 0 for rc in rcs):
            return attempt
        if attempt >= max_restarts:
            raise RuntimeError(
                f"gang failed after {attempt + 1} rounds "
                f"(last exit codes: {rcs})")
    raise AssertionError("unreachable")


# --- controller entrypoint (the promoted multicontroller worker) -----------


def _demo_table(seed: int, n: int, f: int):
    """Deterministic data every controller regenerates from the seed; a
    real deployment reads per-host files instead (the discipline of
    ``tests/multicontroller_worker.py``)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.2 * X[:, 2] > 0).astype(np.float64)
    return X, y


def run_worker(args) -> int:
    """One elastic controller: rendezvous (retried) → watchdog → a
    sharded ``train()`` with ``checkpoint_dir`` live → stats dump.

    Each process owns ONE data shard and passes ``None`` in every other
    slot — no host ever sees another host's rows."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    enable_cpu_collectives()

    # cfg is the single source of the elastic knobs; the rendezvous
    # reads its retry budget from here, not from argparse directly
    cfg = ElasticConfig(
        heartbeat_dir=args.heartbeat_dir, process_id=args.process_id,
        num_processes=args.num_processes,
        heartbeat_interval_s=args.heartbeat_interval,
        straggler_age_s=args.straggler_age,
        lease_timeout_s=args.lease_timeout,
        init_retries=args.init_retries, init_backoff_s=args.init_backoff,
        transport_address=getattr(args, "heartbeat_transport", ""),
        transport_token=getattr(args, "heartbeat_token", ""))

    retry_used = initialize_with_retry(
        args.coordinator, args.num_processes, args.process_id,
        retries=cfg.init_retries, backoff_s=cfg.init_backoff_s)

    import numpy as np
    from jax.sharding import Mesh

    from ..core.mesh import DATA_AXIS, FEATURE_AXIS
    from .binning import fit_bin_mapper
    from .engine import TrainParams, train, train_stats
    from .objectives import get_objective
    write_hook = None
    if args.chaos_heartbeat_stall:
        from ..io.chaos import ChaosHeartbeat
        after_s, stall_s = (float(x) for x
                            in args.chaos_heartbeat_stall.split(":"))
        write_hook = ChaosHeartbeat(after_s=after_s, stall_s=stall_s)
    wd_stats = StageStats()

    def dump_stats() -> None:
        if not args.stats_out:
            return
        snap = {"process_id": args.process_id,
                "rendezvous_retries": retry_used,
                "train": train_stats.snapshot(),
                "watchdog": wd_stats.snapshot(),
                # the controller's journal tail (fit span, boost_chunk,
                # ckpt_*, peer_* events) rides the stats dump so the
                # chaos drill's artifact carries a trace excerpt and
                # trace_report can rebuild the fit timeline post-mortem
                "journal_tail": _tm.get_journal().tail(80)}
        # tmp + atomic replace, per-thread tmp name: the watchdog's
        # on_lost dump (followed by os._exit) can race the main
        # thread's end-of-fit dump to the same path — a direct
        # open(path, "w") truncate-then-die leaves torn JSON that
        # crashes the drill's reader
        tmp = f"{args.stats_out}.{threading.get_ident()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(snap, fh, indent=1)
        os.replace(tmp, args.stats_out)

    def on_lost(pid, age):
        log.error("controller %d lease expired (%.2fs); abandoning "
                  "with RESTART_EXIT_CODE", pid, age)
        dump_stats()
        _tm.record_flight("peer_lost_abandon",
                          {"peer": pid, "age_s": round(age, 3),
                           "process_id": args.process_id})
        os._exit(RESTART_EXIT_CODE)

    wd = HeartbeatWatchdog(cfg, stats=wd_stats, on_peer_lost=on_lost,
                           write_hook=write_hook)
    wd.start()
    if args.chaos_kill_at_boundary > 0 and args.checkpoint_dir:
        from ..io.chaos import ChaosControllerKill
        ChaosControllerKill(args.checkpoint_dir,
                            args.chaos_kill_at_boundary).start()
    try:
        X, y = _demo_table(args.data_seed, args.rows, args.features)
        mapper = fit_bin_mapper(X, max_bin=31)
        D = args.num_processes
        shard_idx = np.array_split(np.arange(args.rows), D)
        shard_rows = [len(i) for i in shard_idx]
        devs = np.asarray(jax.devices()).reshape(D, 1)
        mesh = Mesh(devs, (DATA_AXIS, FEATURE_AXIS))
        slots_b: List = [None] * D
        slots_l: List = [np.asarray(y[i]) for i in shard_idx]
        slots_w: List = [np.ones(len(i), np.float64) for i in shard_idx]
        my = shard_idx[args.process_id]
        slots_b[args.process_id] = mapper.transform_packed(X[my])

        params = TrainParams(
            num_iterations=args.iterations, num_leaves=7,
            bagging_fraction=0.7, bagging_freq=2, feature_fraction=0.8,
            verbosity=0, checkpoint_dir=args.checkpoint_dir,
            checkpoint_chunk=args.checkpoint_chunk)
        booster = train(slots_b, slots_l, slots_w, mapper,
                        get_objective("binary"), params, mesh=mesh,
                        shard_rows=shard_rows)
    finally:
        wd.stop()
    if args.process_id == 0 and args.out:
        with open(args.out, "w") as fh:
            fh.write(booster.save_native_model_string())
    dump_stats()
    print("ELASTIC_OK", flush=True)
    return 0


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="elastic multicontroller training worker")
    ap.add_argument("--coordinator", required=True,
                    help="host:port of the jax.distributed coordinator")
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--heartbeat-dir", required=True)
    ap.add_argument("--heartbeat-transport", default="",
                    help="HOST:PORT of a HeartbeatHub — lease beacons "
                         "ride the unified transport instead of "
                         "shared-filesystem lease files (multi-host)")
    ap.add_argument("--heartbeat-token", default="",
                    help="shared secret for the heartbeat hub")
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--out", default="",
                    help="native model text written by process 0")
    ap.add_argument("--stats-out", default="",
                    help="recovery-counter JSON written on exit")
    ap.add_argument("--iterations", type=int, default=24)
    ap.add_argument("--checkpoint-chunk", type=int, default=6)
    ap.add_argument("--rows", type=int, default=600)
    ap.add_argument("--features", type=int, default=6)
    ap.add_argument("--data-seed", type=int, default=0)
    ap.add_argument("--heartbeat-interval", type=float, default=0.25)
    ap.add_argument("--straggler-age", type=float, default=1.0)
    ap.add_argument("--lease-timeout", type=float, default=5.0)
    ap.add_argument("--init-retries", type=int, default=4)
    ap.add_argument("--init-backoff", type=float, default=0.5)
    ap.add_argument("--chaos-heartbeat-stall", default="",
                    help="AFTER_S:STALL_S — deterministic heartbeat "
                         "stall injection (io.chaos.ChaosHeartbeat)")
    ap.add_argument("--chaos-kill-at-boundary", type=int, default=0,
                    help="SIGKILL this controller once the checkpoint "
                         "meta reaches this boundary "
                         "(io.chaos.ChaosControllerKill; 0 disables)")
    args = ap.parse_args(argv)
    return run_worker(args)


if __name__ == "__main__":
    raise SystemExit(main())
