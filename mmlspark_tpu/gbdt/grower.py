"""Leaf-wise histogram tree grower, fully jit-compatible.

TPU-native replacement for LightGBM's ``SerialTreeLearner``/
``DataParallelTreeLearner`` (driven by the reference through
``LGBM_BoosterUpdateOneIter``; SURVEY.md §3.1 hot loop).  Design notes:

* **Static shapes.**  A tree has a fixed budget of ``num_leaves`` leaves and
  ``num_leaves - 1`` internal nodes; growth is a ``fori_loop`` of
  ``num_leaves - 1`` split steps with inactive steps masked out via
  ``lax.cond`` — XLA's answer to LightGBM's dynamic leaf queue.
* **Leaf membership as a vector.**  Instead of partitioned row indices, a
  ``row_leaf`` (n,) assignment vector selects the split leaf's rows by mask;
  leaf-conditional histograms are built from *masked* gradient triples so
  every step has identical shape and cost.
* **Histogram subtraction.**  Each split builds one child histogram and
  derives the sibling by subtraction, exactly like LightGBM.
* **Leaf numbering parity.**  Splitting leaf ``l`` at step ``i`` creates
  internal node ``i``; the left child keeps leaf id ``l`` and the right
  child becomes leaf ``i + 1`` — the same numbering LightGBM uses, so model
  export is a direct array dump.
* **Distributed.**  Pass ``axis_name`` when running under ``shard_map`` with
  rows sharded across the mesh: local histograms are ``psum``-reduced — the
  ICI-collective replacement for LightGBM's socket ``Network::Allreduce``
  (SURVEY.md §5.8).  Feature-axis sharding is layered on in
  :mod:`mmlspark_tpu.gbdt.distributed`.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.histogram import compute_histogram

EPS_GAIN = 1e-10


@dataclass(frozen=True)
class GrowerConfig:
    """Static hyper-parameters (hashable → usable as a jit static arg)."""
    num_leaves: int = 31
    max_depth: int = -1
    num_bins: int = 256
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    hist_method: str = "auto"
    axis_name: Optional[str] = None          # data-parallel psum axis
    feature_axis_name: Optional[str] = None  # feature-parallel axis
    #: categorical split finding (LightGBM Fisher-grouping analog); static
    #: so the no-categorical compile pays zero cost for the extra machinery
    use_categorical: bool = False
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4

    @property
    def cat_words(self) -> int:
        """u32 words per per-node bin bitset."""
        return max(1, (self.num_bins + 31) // 32)


class TreeArrays(NamedTuple):
    """One grown tree.  Children encoding matches LightGBM: a child value
    ``c >= 0`` is an internal node index, ``c < 0`` is leaf ``~c``."""
    node_feat: jnp.ndarray    # (L-1,) i32
    node_bin: jnp.ndarray     # (L-1,) i32 threshold bin (<= goes left)
    node_left: jnp.ndarray    # (L-1,) i32
    node_right: jnp.ndarray   # (L-1,) i32
    node_gain: jnp.ndarray    # (L-1,) f32
    node_value: jnp.ndarray   # (L-1,) f32 internal output (shrinkage applied)
    node_weight: jnp.ndarray  # (L-1,) f32 sum of hessians
    node_count: jnp.ndarray   # (L-1,) f32 row count
    node_is_cat: jnp.ndarray  # (L-1,) i32 1 = categorical split
    node_cat_bits: jnp.ndarray  # (L-1, W) u32 bin-bitset: bit set -> left
    leaf_value: jnp.ndarray   # (L,) f32 (shrinkage applied)
    leaf_weight: jnp.ndarray  # (L,) f32
    leaf_count: jnp.ndarray   # (L,) f32
    num_leaves: jnp.ndarray   # () i32 actual leaves grown


class _GrowState(NamedTuple):
    row_leaf: jnp.ndarray     # (n,) i32
    leaf_hist: jnp.ndarray    # (L, f, B, 3)
    leaf_g: jnp.ndarray       # (L,)
    leaf_h: jnp.ndarray       # (L,)
    leaf_c: jnp.ndarray       # (L,)
    leaf_depth: jnp.ndarray   # (L,) i32
    leaf_parent: jnp.ndarray  # (L,) i32 (-1 for root)
    leaf_is_right: jnp.ndarray  # (L,) bool
    best_gain: jnp.ndarray    # (L,) f32 (-inf when leaf can't split)
    best_feat: jnp.ndarray    # (L,) i32
    best_bin: jnp.ndarray     # (L,) i32
    best_is_cat: jnp.ndarray  # (L,) i32
    best_cat_bits: jnp.ndarray  # (L, W) u32
    tree: TreeArrays


def _threshold_l1(g, l1):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_gain(g, h, cfg: GrowerConfig):
    t = _threshold_l1(g, cfg.lambda_l1)
    return jnp.square(t) / (h + cfg.lambda_l2)


def _leaf_output(g, h, cfg: GrowerConfig):
    t = _threshold_l1(g, cfg.lambda_l1)
    return -t / (h + cfg.lambda_l2)


def _leaf_gain_l2(g, h, l1, l2):
    t = jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)
    return jnp.square(t) / (h + l2)


def _pack_bin_mask(mask: jnp.ndarray, cfg: GrowerConfig) -> jnp.ndarray:
    """(B,) bool bin subset -> (W,) u32 bitset (bit set = bin goes left)."""
    B = mask.shape[0]
    pos = jnp.arange(B)
    vals = jnp.where(mask, jnp.uint32(1) << (pos % 32).astype(jnp.uint32),
                     jnp.uint32(0))
    return jax.ops.segment_sum(vals, pos // 32,
                               num_segments=cfg.cat_words)


def bin_in_bitset(bits: jnp.ndarray, col: jnp.ndarray) -> jnp.ndarray:
    """Membership of bin indices ``col`` in a (W,) u32 bitset → bool."""
    word = bits[col >> 5]
    return ((word >> (col & 31).astype(jnp.uint32)) & 1).astype(bool)


def _find_best_cat_split(hist, parent_g, parent_h, parent_c, cat_allowed,
                         feat_nbins, cfg: GrowerConfig):
    """Best categorical split: per-feature gradient-ratio-sorted subset scan
    (LightGBM's Fisher-grouping sorted-histogram search) plus a one-vs-rest
    scan for low-cardinality features (max_cat_to_onehot)."""
    B = hist.shape[1]
    g_b, h_b, c_b = hist[..., 0], hist[..., 1], hist[..., 2]
    # The trailing missing bin (NaN + overflow categories) may never join a
    # left subset: it must route RIGHT both in binned training and in raw
    # prediction, where rare/unseen values fail the bitset test.  (LightGBM
    # likewise sends unseen categories right.)
    not_missing = (jnp.arange(B) != B - 1)[None, :]
    nonzero = (c_b > 0) & not_missing
    l2c = cfg.lambda_l2 + cfg.cat_l2
    parent_gain = _leaf_gain_l2(parent_g, parent_h, cfg.lambda_l1, l2c)
    md, mh = cfg.min_data_in_leaf, cfg.min_sum_hessian_in_leaf

    # sorted-prefix scan: order bins by g/(h + cat_smooth), ascending;
    # a prefix of the sorted order is the candidate left subset
    ratio = jnp.where(nonzero, g_b / (h_b + cfg.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1)                       # (f, B)
    hist_s = jnp.take_along_axis(hist, order[:, :, None], axis=1)
    cums = jnp.cumsum(hist_s, axis=1)
    gls, hls, cls = cums[..., 0], cums[..., 1], cums[..., 2]
    grs, hrs, crs = parent_g - gls, parent_h - hls, parent_c - cls
    nz_cnt = jnp.sum(nonzero, axis=1).astype(jnp.float32)    # (f,)
    used_left = (jnp.arange(B) + 1).astype(jnp.float32)[None, :]
    used_right = nz_cnt[:, None] - used_left
    valid_s = ((cls >= md) & (crs >= md) & (hls >= mh) & (hrs >= mh)
               & (used_right >= 1)
               & (jnp.minimum(used_left, used_right)
                  <= cfg.max_cat_threshold))
    gains_s = (_leaf_gain_l2(gls, hls, cfg.lambda_l1, l2c)
               + _leaf_gain_l2(grs, hrs, cfg.lambda_l1, l2c) - parent_gain)
    gains_s = jnp.where(valid_s, gains_s, -jnp.inf)

    # one-vs-rest scan for small-cardinality features (missing bin is
    # excluded via `nonzero`)
    gr1, hr1, cr1 = parent_g - g_b, parent_h - h_b, parent_c - c_b
    valid_1 = (nonzero & (c_b >= md) & (cr1 >= md) & (h_b >= mh)
               & (hr1 >= mh) & (nz_cnt[:, None] >= 2))
    gains_1 = (_leaf_gain_l2(g_b, h_b, cfg.lambda_l1, l2c)
               + _leaf_gain_l2(gr1, hr1, cfg.lambda_l1, l2c) - parent_gain)
    gains_1 = jnp.where(valid_1, gains_1, -jnp.inf)

    use_onehot = (feat_nbins <= cfg.max_cat_to_onehot)       # (f,)
    gains_cat = jnp.where(use_onehot[:, None], gains_1, gains_s)
    gains_cat = jnp.where(cat_allowed[:, None], gains_cat, -jnp.inf)
    flat = gains_cat.reshape(-1)
    idx = jnp.argmax(flat)
    gain = flat[idx]
    feat = (idx // B).astype(jnp.int32)
    k = (idx % B).astype(jnp.int32)

    onehot_win = use_onehot[feat]
    mask_onehot = jnp.arange(B) == k
    prefix = jnp.arange(B) <= k                  # positions in sorted order
    mask_sorted = jnp.zeros(B, bool).at[order[feat]].set(prefix)
    mask_bins = jnp.where(onehot_win, mask_onehot, mask_sorted)
    return gain, feat, k, _pack_bin_mask(mask_bins, cfg)


def find_best_split(hist: jnp.ndarray, parent_g, parent_h, parent_c,
                    feat_info: jnp.ndarray, depth_ok,
                    cfg: GrowerConfig) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                jnp.ndarray, jnp.ndarray,
                                                jnp.ndarray]:
    """Best split over a (f, B, 3) histogram.

    ``feat_info``: (f, 3) float32 — [:, 0] feature mask, [:, 1] categorical
    flag, [:, 2] per-feature value-bin count.  Returns ``(gain, feature,
    bin, is_cat, cat_bits)`` where ``cat_bits`` is the (W,) u32 left-subset
    bin bitset (zeros for numeric splits).

    Numeric path mirrors LightGBM's FindBestThreshold: left = bins <= b,
    validity by min_data_in_leaf / min_sum_hessian, gain = ΔL over the
    parent leaf; first-occurrence argmax reproduces LightGBM's ascending
    scan tie-break.  Categorical path: :func:`_find_best_cat_split`.
    """
    feature_mask = feat_info[:, 0]
    is_cat_f = feat_info[:, 1] > 0
    cum = jnp.cumsum(hist, axis=1)           # (f, B, 3)
    gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
    gr = parent_g - gl
    hr = parent_h - hl
    cr = parent_c - cl
    valid = ((cl >= cfg.min_data_in_leaf) & (cr >= cfg.min_data_in_leaf)
             & (hl >= cfg.min_sum_hessian_in_leaf)
             & (hr >= cfg.min_sum_hessian_in_leaf))
    # cannot split on the last bin (nothing to the right)
    valid = valid & (jnp.arange(hist.shape[1]) < hist.shape[1] - 1)[None, :]
    parent_gain = _leaf_gain(parent_g, parent_h, cfg)
    gains = (_leaf_gain(gl, hl, cfg) + _leaf_gain(gr, hr, cfg) - parent_gain)
    num_allowed = (feature_mask > 0) & (~is_cat_f if cfg.use_categorical
                                        else True)
    gains = jnp.where(valid & num_allowed[:, None] & depth_ok,
                      gains, -jnp.inf)
    flat = gains.reshape(-1)
    idx = jnp.argmax(flat)
    best_gain = flat[idx]
    feat = (idx // hist.shape[1]).astype(jnp.int32)
    b = (idx % hist.shape[1]).astype(jnp.int32)
    is_cat = jnp.asarray(0, jnp.int32)
    cat_bits = jnp.zeros(cfg.cat_words, jnp.uint32)
    if cfg.use_categorical:
        cat_allowed = is_cat_f & (feature_mask > 0) & depth_ok
        cat_gain, cat_feat, _, cat_bits_w = _find_best_cat_split(
            hist, parent_g, parent_h, parent_c, cat_allowed,
            feat_info[:, 2], cfg)
        cat_wins = cat_gain > best_gain
        best_gain = jnp.maximum(best_gain, cat_gain)
        feat = jnp.where(cat_wins, cat_feat, feat)
        b = jnp.where(cat_wins, 0, b)
        is_cat = cat_wins.astype(jnp.int32)
        cat_bits = jnp.where(cat_wins, cat_bits_w, cat_bits)
    if cfg.feature_axis_name is not None:
        # feature-parallel learner: each shard scanned its feature slice;
        # allgather candidate splits and pick the global winner
        # (LightGBM tree_learner=feature analog, SURVEY.md §2.3).
        ax = cfg.feature_axis_name
        gains_all = jax.lax.all_gather(best_gain, ax)       # (S,)
        feats_all = jax.lax.all_gather(feat, ax)
        bins_all = jax.lax.all_gather(b, ax)
        cats_all = jax.lax.all_gather(is_cat, ax)
        bits_all = jax.lax.all_gather(cat_bits, ax)         # (S, W)
        shard = jnp.argmax(gains_all)
        n_local = jnp.asarray(hist.shape[0], jnp.int32)
        best_gain = gains_all[shard]
        feat = feats_all[shard] + shard.astype(jnp.int32) * n_local
        b = bins_all[shard]
        is_cat = cats_all[shard]
        cat_bits = bits_all[shard]
    gain_ok = best_gain > jnp.maximum(cfg.min_gain_to_split, EPS_GAIN)
    return (jnp.where(gain_ok, best_gain, -jnp.inf), feat, b, is_cat,
            cat_bits)


def _hist(bins, gh, cfg: GrowerConfig):
    h = compute_histogram(bins, gh, cfg.num_bins, method=cfg.hist_method)
    if cfg.axis_name is not None:
        h = jax.lax.psum(h, cfg.axis_name)
    return h


def _totals_from_hist(hist):
    """Leaf totals via any one feature's bins (they partition the rows)."""
    s = jnp.sum(hist[0], axis=0)             # (3,)
    return s[0], s[1], s[2]


@functools.partial(jax.jit, static_argnames=("cfg",))
def grow_tree(bins: jnp.ndarray, gh: jnp.ndarray,
              feat_info: jnp.ndarray,
              cfg: GrowerConfig) -> Tuple[TreeArrays, jnp.ndarray]:
    """Grow one tree.  ``gh``: (n, 3) masked (grad, hess, count);
    ``feat_info``: (f, 3) [mask, is_cat, n_value_bins] (see
    :func:`make_feat_info`)."""
    return _grow_tree_impl(bins, gh, feat_info, cfg)


def make_feat_info(f: int, feature_mask=None, is_cat=None, nbins=None):
    """Assemble the (f, 3) feature-info array the grower consumes."""
    import numpy as np
    out = np.zeros((f, 3), np.float32)
    out[:, 0] = 1.0 if feature_mask is None else feature_mask
    if is_cat is not None:
        out[:, 1] = is_cat
    if nbins is not None:
        out[:, 2] = nbins
    return out


def _grow_tree_impl(bins, gh, feat_info, cfg: GrowerConfig):
    n, f = bins.shape
    L = cfg.num_leaves
    W = cfg.cat_words
    neg_inf = jnp.float32(-jnp.inf)

    hist0 = _hist(bins, gh, cfg)
    g0, h0, c0 = _totals_from_hist(hist0)
    depth0_ok = (cfg.max_depth <= 0) | (0 < cfg.max_depth)
    bg0, bf0, bb0, bc0, bits0 = find_best_split(
        hist0, g0, h0, c0, feat_info, jnp.asarray(depth0_ok), cfg)

    tree = TreeArrays(
        node_feat=jnp.zeros(L - 1, jnp.int32),
        node_bin=jnp.zeros(L - 1, jnp.int32),
        node_left=jnp.zeros(L - 1, jnp.int32),
        node_right=jnp.zeros(L - 1, jnp.int32),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_weight=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        node_is_cat=jnp.zeros(L - 1, jnp.int32),
        node_cat_bits=jnp.zeros((L - 1, W), jnp.uint32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(
            _leaf_output(g0, h0, cfg)),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(h0),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(c0),
        num_leaves=jnp.asarray(1, jnp.int32),
    )
    state = _GrowState(
        row_leaf=jnp.zeros(n, jnp.int32),
        leaf_hist=jnp.zeros((L, f, cfg.num_bins, 3), jnp.float32
                            ).at[0].set(hist0),
        leaf_g=jnp.zeros(L, jnp.float32).at[0].set(g0),
        leaf_h=jnp.zeros(L, jnp.float32).at[0].set(h0),
        leaf_c=jnp.zeros(L, jnp.float32).at[0].set(c0),
        leaf_depth=jnp.zeros(L, jnp.int32),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_is_right=jnp.zeros(L, bool),
        best_gain=jnp.full(L, neg_inf).at[0].set(bg0),
        best_feat=jnp.zeros(L, jnp.int32).at[0].set(bf0),
        best_bin=jnp.zeros(L, jnp.int32).at[0].set(bb0),
        best_is_cat=jnp.zeros(L, jnp.int32).at[0].set(bc0),
        best_cat_bits=jnp.zeros((L, W), jnp.uint32).at[0].set(bits0),
        tree=tree,
    )

    def split_step(i, state: _GrowState) -> _GrowState:
        l = jnp.argmax(state.best_gain).astype(jnp.int32)
        gain = state.best_gain[l]
        do_split = gain > neg_inf

        def do(state: _GrowState) -> _GrowState:
            feat = state.best_feat[l]
            thr = state.best_bin[l]
            new_id = (i + 1).astype(jnp.int32)
            if cfg.feature_axis_name is not None:
                # feat is a GLOBAL index but bins holds this shard's feature
                # slice: the owning shard contributes the split column, the
                # psum broadcasts it (LightGBM feature-parallel's bitmap
                # broadcast, as an ICI collective).
                f_local = bins.shape[1]
                shard = jax.lax.axis_index(cfg.feature_axis_name)
                owner = feat // f_local
                lidx = feat - owner * f_local
                col_local = jnp.where(
                    owner == shard,
                    jnp.take(bins, jnp.minimum(lidx, f_local - 1), axis=1),
                    0)
                col = jax.lax.psum(col_local, cfg.feature_axis_name)
            else:
                col = jnp.take(bins, feat, axis=1)
            in_leaf = state.row_leaf == l
            if cfg.use_categorical:
                go_left_val = jnp.where(
                    state.best_is_cat[l] > 0,
                    bin_in_bitset(state.best_cat_bits[l], col),
                    col <= thr)
                go_right = in_leaf & ~go_left_val
            else:
                go_right = in_leaf & (col > thr)
            row_leaf = jnp.where(go_right, new_id, state.row_leaf)

            hist_r = _hist(bins, gh * go_right[:, None], cfg)
            hist_l = state.leaf_hist[l] - hist_r
            g_r, h_r, c_r = _totals_from_hist(hist_r)
            g_l = state.leaf_g[l] - g_r
            h_l = state.leaf_h[l] - h_r
            c_l = state.leaf_c[l] - c_r

            child_depth = state.leaf_depth[l] + 1
            depth_ok = jnp.asarray(
                (cfg.max_depth <= 0), bool) | (child_depth < cfg.max_depth)
            bg_l, bf_l, bb_l, bc_l, bits_l = find_best_split(
                hist_l, g_l, h_l, c_l, feat_info, depth_ok, cfg)
            bg_r, bf_r, bb_r, bc_r, bits_r = find_best_split(
                hist_r, g_r, h_r, c_r, feat_info, depth_ok, cfg)

            t = state.tree
            # link the new internal node into its parent
            p = state.leaf_parent[l]
            has_parent = p >= 0
            p_safe = jnp.maximum(p, 0)
            was_right = state.leaf_is_right[l]
            node_left = t.node_left.at[p_safe].set(
                jnp.where(has_parent & ~was_right, i, t.node_left[p_safe]))
            node_right = t.node_right.at[p_safe].set(
                jnp.where(has_parent & was_right, i, t.node_right[p_safe]))
            tree = t._replace(
                node_feat=t.node_feat.at[i].set(feat),
                node_bin=t.node_bin.at[i].set(thr),
                node_is_cat=t.node_is_cat.at[i].set(state.best_is_cat[l]),
                node_cat_bits=t.node_cat_bits.at[i].set(
                    state.best_cat_bits[l]),
                node_left=node_left.at[i].set(-(l + 1)),
                node_right=node_right.at[i].set(-(new_id + 1)),
                node_gain=t.node_gain.at[i].set(gain),
                node_value=t.node_value.at[i].set(
                    _leaf_output(state.leaf_g[l], state.leaf_h[l], cfg)),
                node_weight=t.node_weight.at[i].set(state.leaf_h[l]),
                node_count=t.node_count.at[i].set(state.leaf_c[l]),
                leaf_value=t.leaf_value
                    .at[l].set(_leaf_output(g_l, h_l, cfg))
                    .at[new_id].set(_leaf_output(g_r, h_r, cfg)),
                leaf_weight=t.leaf_weight.at[l].set(h_l).at[new_id].set(h_r),
                leaf_count=t.leaf_count.at[l].set(c_l).at[new_id].set(c_r),
                num_leaves=t.num_leaves + 1,
            )
            return _GrowState(
                row_leaf=row_leaf,
                leaf_hist=state.leaf_hist.at[l].set(hist_l)
                                         .at[new_id].set(hist_r),
                leaf_g=state.leaf_g.at[l].set(g_l).at[new_id].set(g_r),
                leaf_h=state.leaf_h.at[l].set(h_l).at[new_id].set(h_r),
                leaf_c=state.leaf_c.at[l].set(c_l).at[new_id].set(c_r),
                leaf_depth=state.leaf_depth.at[l].set(child_depth)
                                           .at[new_id].set(child_depth),
                leaf_parent=state.leaf_parent.at[l].set(i)
                                             .at[new_id].set(i),
                leaf_is_right=state.leaf_is_right.at[l].set(False)
                                                 .at[new_id].set(True),
                best_gain=state.best_gain.at[l].set(bg_l)
                                         .at[new_id].set(bg_r),
                best_feat=state.best_feat.at[l].set(bf_l)
                                         .at[new_id].set(bf_r),
                best_bin=state.best_bin.at[l].set(bb_l)
                                       .at[new_id].set(bb_r),
                best_is_cat=state.best_is_cat.at[l].set(bc_l)
                                             .at[new_id].set(bc_r),
                best_cat_bits=state.best_cat_bits.at[l].set(bits_l)
                                                 .at[new_id].set(bits_r),
                tree=tree,
            )

        return jax.lax.cond(do_split, do, lambda s: s, state)

    state = jax.lax.fori_loop(0, L - 1, split_step, state)
    return state.tree, state.row_leaf


def apply_shrinkage(tree: TreeArrays, learning_rate: float) -> TreeArrays:
    return tree._replace(
        leaf_value=tree.leaf_value * learning_rate,
        node_value=tree.node_value * learning_rate)


@functools.partial(jax.jit, static_argnames=("max_steps",))
def predict_tree_binned(tree: TreeArrays, bins: jnp.ndarray,
                        max_steps: int) -> jnp.ndarray:
    """Score binned rows through one tree (used for validation sets)."""
    n = bins.shape[0]

    def body(_, node):
        is_leaf = node < 0
        safe = jnp.maximum(node, 0)
        feat = tree.node_feat[safe]
        thr = tree.node_bin[safe]
        val = jnp.take_along_axis(
            bins, feat[:, None], axis=1)[:, 0]
        go_left = val <= thr
        # categorical nodes: left iff the row's bin is in the subset bitset
        words = jnp.take_along_axis(tree.node_cat_bits[safe],
                                    (val >> 5)[:, None], axis=1)[:, 0]
        left_cat = ((words >> (val & 31).astype(jnp.uint32)) & 1
                    ).astype(bool)
        go_left = jnp.where(tree.node_is_cat[safe] > 0, left_cat, go_left)
        nxt = jnp.where(go_left, tree.node_left[safe],
                        tree.node_right[safe])
        return jnp.where(is_leaf, node, nxt)

    start = jnp.where(tree.num_leaves > 1,
                      jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32))
    node = jax.lax.fori_loop(0, max_steps, body, start)
    leaf = -(node + 1)
    return tree.leaf_value[leaf]
