"""Leaf-wise histogram tree grower, fully jit-compatible.

TPU-native replacement for LightGBM's ``SerialTreeLearner``/
``DataParallelTreeLearner`` (driven by the reference through
``LGBM_BoosterUpdateOneIter``; SURVEY.md §3.1 hot loop).  Design notes:

* **Static shapes.**  A tree has a fixed budget of ``num_leaves`` leaves and
  ``num_leaves - 1`` internal nodes; growth is a ``fori_loop`` of
  ``num_leaves - 1`` split steps with inactive steps masked out via
  ``lax.cond`` — XLA's answer to LightGBM's dynamic leaf queue.
* **Leaf membership as a vector.**  Instead of partitioned row indices, a
  ``row_leaf`` (n,) assignment vector selects the split leaf's rows by mask;
  leaf-conditional histograms are built from *masked* gradient triples so
  every step has identical shape and cost.
* **Histogram subtraction.**  Each split builds one child histogram and
  derives the sibling by subtraction, exactly like LightGBM.
* **Leaf numbering parity.**  Splitting leaf ``l`` at step ``i`` creates
  internal node ``i``; the left child keeps leaf id ``l`` and the right
  child becomes leaf ``i + 1`` — the same numbering LightGBM uses, so model
  export is a direct array dump.
* **Distributed.**  Pass ``axis_name`` when running under ``shard_map`` with
  rows sharded across the mesh: local histograms are ``psum``-reduced — the
  ICI-collective replacement for LightGBM's socket ``Network::Allreduce``
  (SURVEY.md §5.8).  Feature-axis sharding is layered on in
  :mod:`mmlspark_tpu.gbdt.distributed`.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.histogram import compute_histogram

EPS_GAIN = 1e-10


class EFBArrays(NamedTuple):
    """Device-side EFB expansion maps (see gbdt/efb.py): bins holds G
    bundle columns; histograms and split columns reconstruct per ORIGINAL
    feature through these static-shaped arrays."""
    gather_idx: jnp.ndarray   # (f, B) i32 flat (bundle*B + bundle_bin)
    valid: jnp.ndarray        # (f, B) bool bins feature j actually uses
    bundle_of: jnp.ndarray    # (f,) i32
    off_of: jnp.ndarray       # (f,) i32
    nb_of: jnp.ndarray        # (f,) i32
    default_of: jnp.ndarray   # (f,) i32


def _efb_expand(hist_b, efb):
    """(G, B, 3) bundle histogram -> exact (f, B, 3) per-feature histogram.

    Member slices come from a static flat gather; each feature's default
    bin - whose rows the bundle encodes implicitly as "not this member" -
    is reconstituted as leaf_total minus the explicit bins.  Bundle 0's
    bins partition every row, so its sum IS the leaf total.
    """
    f = efb.gather_idx.shape[0]
    flat = hist_b.reshape(-1, hist_b.shape[-1])          # (G*B, 3)
    hist = jnp.take(flat, efb.gather_idx.reshape(-1), axis=0)
    hist = hist.reshape(f, hist_b.shape[1], hist_b.shape[2])
    hist = hist * efb.valid[:, :, None]
    tot = jnp.sum(hist_b[0], axis=0)                      # (3,) leaf total
    deficit = tot[None, :] - jnp.sum(hist, axis=1)        # (f, 3)
    return hist.at[jnp.arange(f), efb.default_of].add(deficit)


def efb_feature_column(binsT, feat, efb, num_bins):
    """Reconstruct original feature ``feat``'s bin column from its bundle
    column: in-range values shift back by the member offset (the last
    member slot is the NaN bin), everything else is the default bin."""
    g = efb.bundle_of[feat]
    bcol = jnp.take(binsT, g, axis=0).astype(jnp.int32)
    off = efb.off_of[feat]
    nb = efb.nb_of[feat]
    raw = bcol - off
    inr = (raw >= 0) & (raw <= nb)
    return jnp.where(inr, jnp.where(raw == nb, num_bins - 1, raw),
                     efb.default_of[feat])


@dataclass(frozen=True)
class GrowerConfig:
    """Static hyper-parameters (hashable → usable as a jit static arg)."""
    num_leaves: int = 31
    max_depth: int = -1
    num_bins: int = 256
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    hist_method: str = "auto"
    #: histogram only the smaller child's rows, gathered into a power-of-two
    #: bucket picked by ``lax.switch`` (LightGBM's DataPartition +
    #: smaller-child trick, re-shaped for static-shape jit); the sibling
    #: comes from subtraction.  ~L full-data scans per tree become ~2-3
    #: full-data equivalents.  Disable to force full masked scans.
    compact_rows: bool = True
    #: smallest compaction bucket (rows); buckets double up to 2^ceil(lg n)
    min_bucket: int = 2048
    #: gather leaf segments from a (n, ceil(f/4)) uint32 matrix with four
    #: uint8 bins packed per word (unpacked by shift/mask after the
    #: gather, which fuses into the histogram's elementwise prologue).
    #: The per-split row gather touches 4x fewer elements — aimed at the
    #: TPU gather cost PERF.md measured at ~2x the histogram itself.
    #: Requires uint8 bins; ignored otherwise.
    packed_gather: bool = False
    #: PV-Tree voting parallelism (Meng et al. 2016; LightGBM
    #: tree_learner=voting, top_k): > 0 with ``axis_name`` set keeps leaf
    #: histograms SHARD-LOCAL; each shard votes its top-k features by
    #: local gain, votes are allgathered, and only the 2k winning
    #: features' histograms are psum-reduced — comm per split drops from
    #: O(f*B) to O(k*B + votes).
    voting_k: int = 0
    axis_name: Optional[str] = None          # data-parallel psum axis
    feature_axis_name: Optional[str] = None  # feature-parallel axis
    #: cross-shard histogram reduction: "psum" (XLA all-reduce) or
    #: "ring" (Pallas on-chip ring reduce-scatter/all-gather,
    #: ops/pallas_collectives.py).  Resolved by the engine at config
    #: build (resolve_collective); "ring" requires a data-only 1-axis
    #: mesh and silently degrades to psum where the kernel gates refuse.
    collective: str = "psum"
    #: static size of the data mesh axis (the ring kernels need it at
    #: trace time; 1 = serial).  Set by distributed._sharded_cfg.
    data_axis_size: int = 1
    #: categorical split finding (LightGBM Fisher-grouping analog); static
    #: so the no-categorical compile pays zero cost for the extra machinery
    use_categorical: bool = False
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    #: quantized-gradient training (ISSUE 17; Shi et al. NeurIPS 2022,
    #: LightGBM ``use_quantized_grad``): discretize each round's (g, h)
    #: to a symmetric integer grid with seeded stochastic rounding and
    #: accumulate EXACT int32 histograms — the sibling subtraction
    #: becomes bit-exact in integers and the cross-shard reduces carry
    #: low-bit slabs.  0 = off; 8/16 = grid bits.  Resolved by the
    #: engine (_resolve_quantized): ``quantized_max_code`` is the
    #: clamped max |code| (grid half-width, possibly narrowed so the
    #: accumulated slab fits the wire dtype) and ``quantized_wire`` the
    #: psum slab dtype ("none" serial, else "int8"/"int16"/"int32").
    quantized_bits: int = 0
    quantized_seed: int = 0
    quantized_max_code: int = 0
    quantized_wire: str = "none"

    @property
    def cat_words(self) -> int:
        """u32 words per per-node bin bitset."""
        return max(1, (self.num_bins + 31) // 32)


class TreeArrays(NamedTuple):
    """One grown tree.  Children encoding matches LightGBM: a child value
    ``c >= 0`` is an internal node index, ``c < 0`` is leaf ``~c``."""
    node_feat: jnp.ndarray    # (L-1,) i32
    node_bin: jnp.ndarray     # (L-1,) i32 threshold bin (<= goes left)
    node_left: jnp.ndarray    # (L-1,) i32
    node_right: jnp.ndarray   # (L-1,) i32
    node_gain: jnp.ndarray    # (L-1,) f32
    node_value: jnp.ndarray   # (L-1,) f32 internal output (shrinkage applied)
    node_weight: jnp.ndarray  # (L-1,) f32 sum of hessians
    node_count: jnp.ndarray   # (L-1,) f32 row count
    node_is_cat: jnp.ndarray  # (L-1,) i32 1 = categorical split
    node_cat_bits: jnp.ndarray  # (L-1, W) u32 bin-bitset: bit set -> left
    leaf_value: jnp.ndarray   # (L,) f32 (shrinkage applied)
    leaf_weight: jnp.ndarray  # (L,) f32
    leaf_count: jnp.ndarray   # (L,) f32
    num_leaves: jnp.ndarray   # () i32 actual leaves grown


class _GrowState(NamedTuple):
    row_leaf: jnp.ndarray     # (n,) i32 (masked path; (1,) dummy otherwise)
    #: partition-mode row tracking (LightGBM DataPartition analog): a row
    #: permutation with each leaf's rows contiguous, plus per-leaf segment
    #: offsets/lengths.  (1,)/(L,) dummies on the masked path.
    row_order: jnp.ndarray    # (n + n_pow,) i32; entries >= n are sentinels
    leaf_start: jnp.ndarray   # (L,) i32
    leaf_cnt: jnp.ndarray     # (L,) i32
    leaf_hist: jnp.ndarray    # (L, f, B, 3)
    leaf_g: jnp.ndarray       # (L,)
    leaf_h: jnp.ndarray       # (L,)
    leaf_c: jnp.ndarray       # (L,)
    leaf_depth: jnp.ndarray   # (L,) i32
    leaf_parent: jnp.ndarray  # (L,) i32 (-1 for root)
    leaf_is_right: jnp.ndarray  # (L,) bool
    best_gain: jnp.ndarray    # (L,) f32 (-inf when leaf can't split)
    best_feat: jnp.ndarray    # (L,) i32
    best_bin: jnp.ndarray     # (L,) i32
    best_is_cat: jnp.ndarray  # (L,) i32
    best_cat_bits: jnp.ndarray  # (L, W) u32
    tree: TreeArrays


def _threshold_l1(g, l1):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_gain(g, h, cfg: GrowerConfig):
    t = _threshold_l1(g, cfg.lambda_l1)
    return jnp.square(t) / (h + cfg.lambda_l2)


def _leaf_output(g, h, cfg: GrowerConfig):
    t = _threshold_l1(g, cfg.lambda_l1)
    return -t / (h + cfg.lambda_l2)


def _leaf_gain_l2(g, h, l1, l2):
    t = jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)
    return jnp.square(t) / (h + l2)


def _pack_bin_mask(mask: jnp.ndarray, cfg: GrowerConfig) -> jnp.ndarray:
    """(B,) bool bin subset -> (W,) u32 bitset (bit set = bin goes left)."""
    B = mask.shape[0]
    pos = jnp.arange(B)
    vals = jnp.where(mask, jnp.uint32(1) << (pos % 32).astype(jnp.uint32),
                     jnp.uint32(0))
    return jax.ops.segment_sum(vals, pos // 32,
                               num_segments=cfg.cat_words)


def bin_in_bitset(bits: jnp.ndarray, col: jnp.ndarray) -> jnp.ndarray:
    """Membership of bin indices ``col`` in a (W,) u32 bitset → bool."""
    word = bits[col >> 5]
    return ((word >> (col & 31).astype(jnp.uint32)) & 1).astype(bool)


def _cat_split_gains(hist, parent_g, parent_h, parent_c, cat_allowed,
                     feat_nbins, cfg: GrowerConfig):
    """Per-feature categorical split gains: the (f, B) gain matrix plus the
    sorted-bin order and onehot flags needed to reconstruct the winning
    left-subset bitset.  Shared by the exact finder and the voting
    learner's local-vote scoring (which needs per-FEATURE maxima, not the
    global argmax)."""
    B = hist.shape[1]
    g_b, h_b, c_b = hist[..., 0], hist[..., 1], hist[..., 2]
    # The trailing missing bin (NaN + overflow categories) may never join a
    # left subset: it must route RIGHT both in binned training and in raw
    # prediction, where rare/unseen values fail the bitset test.  (LightGBM
    # likewise sends unseen categories right.)
    not_missing = (jnp.arange(B) != B - 1)[None, :]
    nonzero = (c_b > 0) & not_missing
    l2c = cfg.lambda_l2 + cfg.cat_l2
    parent_gain = _leaf_gain_l2(parent_g, parent_h, cfg.lambda_l1, l2c)
    md, mh = cfg.min_data_in_leaf, cfg.min_sum_hessian_in_leaf

    # sorted-prefix scan: order bins by g/(h + cat_smooth), ascending;
    # a prefix of the sorted order is the candidate left subset
    ratio = jnp.where(nonzero, g_b / (h_b + cfg.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1)                       # (f, B)
    hist_s = jnp.take_along_axis(hist, order[:, :, None], axis=1)
    cums = jnp.cumsum(hist_s, axis=1)
    gls, hls, cls = cums[..., 0], cums[..., 1], cums[..., 2]
    grs, hrs, crs = parent_g - gls, parent_h - hls, parent_c - cls
    nz_cnt = jnp.sum(nonzero, axis=1).astype(jnp.float32)    # (f,)
    used_left = (jnp.arange(B) + 1).astype(jnp.float32)[None, :]
    used_right = nz_cnt[:, None] - used_left
    valid_s = ((cls >= md) & (crs >= md) & (hls >= mh) & (hrs >= mh)
               & (used_right >= 1)
               & (jnp.minimum(used_left, used_right)
                  <= cfg.max_cat_threshold))
    gains_s = (_leaf_gain_l2(gls, hls, cfg.lambda_l1, l2c)
               + _leaf_gain_l2(grs, hrs, cfg.lambda_l1, l2c) - parent_gain)
    gains_s = jnp.where(valid_s, gains_s, -jnp.inf)

    # one-vs-rest scan for small-cardinality features (missing bin is
    # excluded via `nonzero`)
    gr1, hr1, cr1 = parent_g - g_b, parent_h - h_b, parent_c - c_b
    valid_1 = (nonzero & (c_b >= md) & (cr1 >= md) & (h_b >= mh)
               & (hr1 >= mh) & (nz_cnt[:, None] >= 2))
    gains_1 = (_leaf_gain_l2(g_b, h_b, cfg.lambda_l1, l2c)
               + _leaf_gain_l2(gr1, hr1, cfg.lambda_l1, l2c) - parent_gain)
    gains_1 = jnp.where(valid_1, gains_1, -jnp.inf)

    use_onehot = (feat_nbins <= cfg.max_cat_to_onehot)       # (f,)
    gains_cat = jnp.where(use_onehot[:, None], gains_1, gains_s)
    gains_cat = jnp.where(cat_allowed[:, None], gains_cat, -jnp.inf)
    return gains_cat, order, use_onehot


def _find_best_cat_split(hist, parent_g, parent_h, parent_c, cat_allowed,
                         feat_nbins, cfg: GrowerConfig):
    """Best categorical split: per-feature gradient-ratio-sorted subset scan
    (LightGBM's Fisher-grouping sorted-histogram search) plus a one-vs-rest
    scan for low-cardinality features (max_cat_to_onehot)."""
    B = hist.shape[1]
    gains_cat, order, use_onehot = _cat_split_gains(
        hist, parent_g, parent_h, parent_c, cat_allowed, feat_nbins, cfg)
    flat = gains_cat.reshape(-1)
    idx = jnp.argmax(flat)
    gain = flat[idx]
    feat = (idx // B).astype(jnp.int32)
    k = (idx % B).astype(jnp.int32)

    onehot_win = use_onehot[feat]
    mask_onehot = jnp.arange(B) == k
    prefix = jnp.arange(B) <= k                  # positions in sorted order
    mask_sorted = jnp.zeros(B, bool).at[order[feat]].set(prefix)
    mask_bins = jnp.where(onehot_win, mask_onehot, mask_sorted)
    return gain, feat, k, _pack_bin_mask(mask_bins, cfg)


def find_best_split(hist: jnp.ndarray, parent_g, parent_h, parent_c,
                    feat_info: jnp.ndarray, depth_ok,
                    cfg: GrowerConfig) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                jnp.ndarray, jnp.ndarray,
                                                jnp.ndarray]:
    """Best split over a (f, B, 3) histogram.

    ``feat_info``: (f, 3) float32 — [:, 0] feature mask, [:, 1] categorical
    flag, [:, 2] per-feature value-bin count.  Returns ``(gain, feature,
    bin, is_cat, cat_bits)`` where ``cat_bits`` is the (W,) u32 left-subset
    bin bitset (zeros for numeric splits).

    Numeric path mirrors LightGBM's FindBestThreshold: left = bins <= b,
    validity by min_data_in_leaf / min_sum_hessian, gain = ΔL over the
    parent leaf; first-occurrence argmax reproduces LightGBM's ascending
    scan tie-break.  Categorical path: :func:`_find_best_cat_split`.
    """
    feature_mask = feat_info[:, 0]
    is_cat_f = feat_info[:, 1] > 0
    cum = jnp.cumsum(hist, axis=1)           # (f, B, 3)
    gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
    gr = parent_g - gl
    hr = parent_h - hl
    cr = parent_c - cl
    valid = ((cl >= cfg.min_data_in_leaf) & (cr >= cfg.min_data_in_leaf)
             & (hl >= cfg.min_sum_hessian_in_leaf)
             & (hr >= cfg.min_sum_hessian_in_leaf))
    # cannot split on the last bin (nothing to the right)
    valid = valid & (jnp.arange(hist.shape[1]) < hist.shape[1] - 1)[None, :]
    parent_gain = _leaf_gain(parent_g, parent_h, cfg)
    gains = (_leaf_gain(gl, hl, cfg) + _leaf_gain(gr, hr, cfg) - parent_gain)
    num_allowed = (feature_mask > 0) & (~is_cat_f if cfg.use_categorical
                                        else True)
    gains = jnp.where(valid & num_allowed[:, None] & depth_ok,
                      gains, -jnp.inf)
    flat = gains.reshape(-1)
    idx = jnp.argmax(flat)
    best_gain = flat[idx]
    feat = (idx // hist.shape[1]).astype(jnp.int32)
    b = (idx % hist.shape[1]).astype(jnp.int32)
    is_cat = jnp.asarray(0, jnp.int32)
    cat_bits = jnp.zeros(cfg.cat_words, jnp.uint32)
    if cfg.use_categorical:
        cat_allowed = is_cat_f & (feature_mask > 0) & depth_ok
        cat_gain, cat_feat, _, cat_bits_w = _find_best_cat_split(
            hist, parent_g, parent_h, parent_c, cat_allowed,
            feat_info[:, 2], cfg)
        cat_wins = cat_gain > best_gain
        best_gain = jnp.maximum(best_gain, cat_gain)
        feat = jnp.where(cat_wins, cat_feat, feat)
        b = jnp.where(cat_wins, 0, b)
        is_cat = cat_wins.astype(jnp.int32)
        cat_bits = jnp.where(cat_wins, cat_bits_w, cat_bits)
    if cfg.feature_axis_name is not None:
        # feature-parallel learner: each shard scanned its feature slice;
        # allgather candidate splits and pick the global winner
        # (LightGBM tree_learner=feature analog, SURVEY.md §2.3).
        ax = cfg.feature_axis_name
        gains_all = jax.lax.all_gather(best_gain, ax)       # (S,)
        feats_all = jax.lax.all_gather(feat, ax)
        bins_all = jax.lax.all_gather(b, ax)
        cats_all = jax.lax.all_gather(is_cat, ax)
        bits_all = jax.lax.all_gather(cat_bits, ax)         # (S, W)
        shard = jnp.argmax(gains_all)
        n_local = jnp.asarray(hist.shape[0], jnp.int32)
        best_gain = gains_all[shard]
        feat = feats_all[shard] + shard.astype(jnp.int32) * n_local
        b = bins_all[shard]
        is_cat = cats_all[shard]
        cat_bits = bits_all[shard]
    gain_ok = best_gain > jnp.maximum(cfg.min_gain_to_split, EPS_GAIN)
    return (jnp.where(gain_ok, best_gain, -jnp.inf), feat, b, is_cat,
            cat_bits)


def _is_voting(cfg: GrowerConfig) -> bool:
    return cfg.axis_name is not None and cfg.voting_k > 0


def _is_quantized(cfg: GrowerConfig) -> bool:
    return cfg.quantized_bits > 0 and cfg.quantized_max_code > 0


def _quantize_gh(gh, cfg: GrowerConfig):
    """Discretize the round's ``(n, 3)`` float gh triple to integer grid
    codes with seeded stochastic rounding (ISSUE 17 tentpole).

    The grid scale comes from the round's GLOBAL max-abs (``pmax`` under
    a data mesh, so every shard quantizes on the identical grid and the
    reduced integer histograms are exact sums of exact codes).  SR —
    ``floor(x) + (u < frac(x))`` — keeps the code expectation unbiased;
    the PRNG key folds the g-scale's bit pattern into
    ``cfg.quantized_seed``, so the same seed + data is bit-reproducible
    while every boost round draws fresh noise.  The count channel is the
    0/1 bag mask and casts exactly.  Returns ``(codes (n, 3) int32,
    scale (3,) f32)`` with ``codes * scale`` the dequantization."""
    mc = cfg.quantized_max_code
    gmax = jnp.max(jnp.abs(gh[:, 0]))
    hmax = jnp.max(jnp.abs(gh[:, 1]))
    if cfg.axis_name is not None and cfg.data_axis_size > 1:
        gmax = jax.lax.pmax(gmax, cfg.axis_name)
        hmax = jax.lax.pmax(hmax, cfg.axis_name)
    gs = jnp.maximum(gmax, jnp.float32(1e-30)) / mc
    hs = jnp.maximum(hmax, jnp.float32(1e-30)) / mc
    key = jax.random.fold_in(
        jax.random.PRNGKey(cfg.quantized_seed),
        jax.lax.bitcast_convert_type(gmax.astype(jnp.float32), jnp.int32))
    u = jax.random.uniform(key, (gh.shape[0], 2))
    x = gh[:, :2] / jnp.stack([gs, hs])[None, :]
    lo = jnp.floor(x)
    code = lo + (u < (x - lo)).astype(jnp.float32)
    code = jnp.clip(code, -mc, mc).astype(jnp.int32)
    codes = jnp.concatenate(
        [code, gh[:, 2:3].astype(jnp.int32)], axis=1)
    scale = jnp.stack([gs, hs, jnp.float32(1.0)])
    return codes, scale


def _wire_cast_psum(h, cfg: GrowerConfig):
    """psum an integer histogram slab at the resolved wire width: the
    engine's headroom analysis (_resolve_quantized) guarantees the
    GLOBAL accumulated magnitude fits the narrow dtype, so the slab
    rides the all-reduce at 1 or 2 bytes/element instead of 4 and the
    sum is still exact."""
    if (cfg.quantized_wire in ("int8", "int16")
            and jnp.issubdtype(h.dtype, jnp.integer)):
        wt = jnp.int8 if cfg.quantized_wire == "int8" else jnp.int16
        return jax.lax.psum(h.astype(wt), cfg.axis_name).astype(h.dtype)
    return jax.lax.psum(h, cfg.axis_name)


def _reduce_hist(h, cfg: GrowerConfig):
    """Cross-shard reduction of a local histogram: ``lax.psum`` or the
    on-chip Pallas ring (ops/pallas_collectives.py) per
    ``cfg.collective``.  The ring entry is trace-safe — it consults only
    the cached Mosaic verdict and falls back to psum when the kernel is
    unavailable or the VMEM gate refuses the state.  Integer (quantized)
    slabs ride the psum at the resolved wire width; the ring's f32 lanes
    round-trip integer sums exactly below 2^24, which the engine's
    resolve gate guarantees before leaving ring enabled."""
    if cfg.collective == "ring" and cfg.data_axis_size > 1:
        from ..ops.pallas_collectives import ring_allreduce_or_psum
        return ring_allreduce_or_psum(h, cfg.axis_name,
                                      cfg.data_axis_size)
    return _wire_cast_psum(h, cfg)


def _hist(bins, gh, cfg: GrowerConfig, efb: Optional[EFBArrays] = None):
    h = compute_histogram(bins, gh, cfg.num_bins, method=cfg.hist_method,
                          max_code=cfg.quantized_max_code)
    if efb is not None:
        # bins holds G bundle columns; expand to per-feature histograms
        # BEFORE any psum — expansion is linear (static gather + a
        # leaf-total subtraction), so shard-local expansion followed by
        # the reduction equals expanding the reduced histogram
        h = _efb_expand(h, efb)
    if cfg.axis_name is not None and not _is_voting(cfg):
        # voting mode keeps histograms shard-local; only the voted
        # candidate slices are ever reduced (find_best_split_voting)
        h = _reduce_hist(h, cfg)
    return h


def _take_cand(hist, cand):
    """Gather candidate columns: ``(f,B,3)[cand (k2,)]`` → ``(k2,B,3)``,
    or batched ``(m,f,B,3)`` with ``cand (m,k2)`` → ``(m,k2,B,3)``."""
    if cand.ndim == 1:
        return jnp.take(hist, cand, axis=0)
    return jnp.take_along_axis(hist, cand[:, :, None, None], axis=1)


def _reduce_select(hist_local, cand, cfg: GrowerConfig):
    """Reduce ONLY the voted candidate columns across the data mesh: the
    voted-column ring (ops/pallas_collectives.ring_allreduce_select)
    when the collective resolved to ring, gather + ``lax.psum``
    otherwise.  Trace-safe like :func:`_reduce_hist` — the ring entry
    consults only the cached Mosaic verdict and the VMEM gate."""
    if cfg.collective == "ring" and cfg.data_axis_size > 1:
        from ..ops.pallas_collectives import ring_allreduce_select_or_psum
        return ring_allreduce_select_or_psum(hist_local, cand,
                                             cfg.axis_name,
                                             cfg.data_axis_size)
    return _wire_cast_psum(_take_cand(hist_local, cand), cfg)


def _voting_masks(feat_info, depth_ok, cfg: GrowerConfig):
    """Per-feature numeric mask and (when categorical) cat-allowed mask
    shared by every phase of the voting protocol."""
    feature_mask = feat_info[:, 0]
    is_cat_f = feat_info[:, 1] > 0
    num_mask = ((feature_mask > 0) & (~is_cat_f if cfg.use_categorical
                                      else True))
    cat_allowed = (is_cat_f & (feature_mask > 0) & depth_ok
                   if cfg.use_categorical else None)
    return num_mask, cat_allowed


def _voting_feature_gains(hist, pg, ph, pc, mask_cols, depth_ok,
                          cfg: GrowerConfig):
    """Per-(feature, bin) numeric split gains over ``hist`` against the
    given parent totals — the scan both the vote and decide phases run."""
    B = hist.shape[1]
    md, mh = cfg.min_data_in_leaf, cfg.min_sum_hessian_in_leaf
    cum = jnp.cumsum(hist, axis=1)
    gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
    gr, hr, cr = pg - gl, ph - hl, pc - cl
    valid = ((cl >= md) & (cr >= md) & (hl >= mh) & (hr >= mh)
             & (jnp.arange(B) < B - 1)[None, :])
    parent_gain = _leaf_gain(pg, ph, cfg)
    gains = (_leaf_gain(gl, hl, cfg) + _leaf_gain(gr, hr, cfg)
             - parent_gain)
    return jnp.where(valid & mask_cols & depth_ok, gains, -jnp.inf)


def _voting_votes(hist_local, feat_info, depth_ok, num_mask, cat_allowed,
                  cfg: GrowerConfig):
    """Shard-local vote: the ids of the top-k features by local best
    gain against the shard's LOCAL leaf totals."""
    f = hist_local.shape[0]
    s_loc = jnp.sum(hist_local[0], axis=0)
    gains_loc = _voting_feature_gains(hist_local, s_loc[0], s_loc[1],
                                      s_loc[2], num_mask[:, None],
                                      depth_ok, cfg)
    score_f = jnp.max(gains_loc, axis=1)
    if cfg.use_categorical:
        gains_cat_loc, _, _ = _cat_split_gains(
            hist_local, s_loc[0], s_loc[1], s_loc[2], cat_allowed,
            feat_info[:, 2], cfg)
        score_f = jnp.maximum(score_f, jnp.max(gains_cat_loc, axis=1))
    _, votes = jax.lax.top_k(score_f, min(cfg.voting_k, f))
    return votes


def _voting_candidates(votes_flat, f: int, cfg: GrowerConfig):
    """Global candidate set from the allgathered votes: top-2k features
    by vote count (feature id tie-break keeps every shard's selection
    identical and deterministic)."""
    counts = jnp.zeros(f, jnp.int32).at[votes_flat].add(1)
    k = min(cfg.voting_k, f)
    k2 = min(2 * k, f)
    key = counts * f + (f - 1 - jnp.arange(f, dtype=jnp.int32))
    _, cand = jax.lax.top_k(key, k2)                             # (k2,)
    return cand


def _voting_decide(hist_cand, cand, pg, ph, pc, feat_info, depth_ok,
                   num_mask, cat_allowed, cfg: GrowerConfig):
    """Exact decision over the globally reduced candidate histograms."""
    B = hist_cand.shape[1]
    gains_cand = _voting_feature_gains(hist_cand, pg, ph, pc,
                                       num_mask[cand][:, None],
                                       depth_ok, cfg)
    flat = gains_cand.reshape(-1)
    idx = jnp.argmax(flat)
    best_gain = flat[idx]
    feat = cand[(idx // B).astype(jnp.int32)]
    b = (idx % B).astype(jnp.int32)
    is_cat = jnp.asarray(0, jnp.int32)
    cat_bits = jnp.zeros(cfg.cat_words, jnp.uint32)
    if cfg.use_categorical:
        cat_gain, cat_feat_loc, _, cat_bits_w = _find_best_cat_split(
            hist_cand, pg, ph, pc, cat_allowed[cand],
            feat_info[cand, 2], cfg)
        cat_wins = cat_gain > best_gain
        best_gain = jnp.maximum(best_gain, cat_gain)
        feat = jnp.where(cat_wins, cand[cat_feat_loc], feat)
        b = jnp.where(cat_wins, 0, b)
        is_cat = cat_wins.astype(jnp.int32)
        cat_bits = jnp.where(cat_wins, cat_bits_w, cat_bits)
    gain_ok = best_gain > jnp.maximum(cfg.min_gain_to_split, EPS_GAIN)
    return (jnp.where(gain_ok, best_gain, -jnp.inf), feat, b, is_cat,
            cat_bits)


def find_best_split_voting(hist_local, parent_g, parent_h, parent_c,
                           feat_info, depth_ok, cfg: GrowerConfig,
                           deq=None):
    """PV-Tree split finding (Meng et al. 2016; LightGBM
    tree_learner=voting): each data shard scores every feature on its
    LOCAL histogram against its LOCAL totals, votes its top-k features,
    votes are allgathered, and only the globally top-2k voted features'
    histograms are reduced — via the voted-column ring or psum per
    ``cfg.collective`` (:func:`_reduce_select`) — for the exact global
    decision.

    Categorical features vote with their local Fisher-grouping gain
    (:func:`_cat_split_gains`) and, when voted into the candidate set, get
    the exact sorted-subset search over the reduced candidate
    histograms — same two-phase shape as the numeric path.
    Returns the same tuple as :func:`find_best_split`.

    ``deq`` (quantized-gradient mode): the votes and the decision run on
    DEQUANTIZED f32 histograms, but the candidate slab crosses the wire
    RAW — the low-bit integer codes ride :func:`_reduce_select` and only
    the reduced slab is dequantized.
    """
    f = hist_local.shape[0]
    num_mask, cat_allowed = _voting_masks(feat_info, depth_ok, cfg)
    # 1. local votes  2. global candidates  3. exact decision over the
    # reduced (k2, B, 3) candidate slab
    votes = _voting_votes(deq(hist_local) if deq else hist_local,
                          feat_info, depth_ok, num_mask, cat_allowed, cfg)
    votes_all = jax.lax.all_gather(votes, cfg.axis_name)        # (S, k)
    cand = _voting_candidates(votes_all.reshape(-1), f, cfg)
    hist_cand = _reduce_select(hist_local, cand, cfg)           # (k2, B, 3)
    if deq is not None:
        hist_cand = deq(hist_cand)
    return _voting_decide(hist_cand, cand, parent_g, parent_h, parent_c,
                          feat_info, depth_ok, num_mask, cat_allowed, cfg)


def find_best_split_voting_pair(hist_l, hist_r, tot_l, tot_r, feat_info,
                                depth_ok, cfg: GrowerConfig, deq=None):
    """Batched-frontier voting for the two children of one grow step:
    both children's votes ride ONE allgather and both candidate slabs
    ONE ``(2, k2, B, 3)`` reduction, so the collective count per grow
    step is 1 candidate reduce instead of 2 — O(depth)-shaped instead of
    O(leaves)-shaped when ``num_leaves ≤ max_depth + 1``.  The stacked
    reduce is element-wise, so results are BIT-IDENTICAL to two
    independent :func:`find_best_split_voting` calls.  ``deq`` as in
    :func:`find_best_split_voting` — the stacked slab crosses the wire
    as raw integer codes and is dequantized after the reduction."""
    f = hist_l.shape[0]
    num_mask, cat_allowed = _voting_masks(feat_info, depth_ok, cfg)
    hl_v = deq(hist_l) if deq else hist_l
    hr_v = deq(hist_r) if deq else hist_r
    votes = jnp.stack([
        _voting_votes(hl_v, feat_info, depth_ok, num_mask, cat_allowed,
                      cfg),
        _voting_votes(hr_v, feat_info, depth_ok, num_mask, cat_allowed,
                      cfg)])
    votes_all = jax.lax.all_gather(votes, cfg.axis_name)     # (S, 2, k)
    cand_l = _voting_candidates(votes_all[:, 0].reshape(-1), f, cfg)
    cand_r = _voting_candidates(votes_all[:, 1].reshape(-1), f, cfg)
    slab = _reduce_select(jnp.stack([hist_l, hist_r]),
                          jnp.stack([cand_l, cand_r]), cfg)  # (2,k2,B,3)
    if deq is not None:
        slab = deq(slab)
    res_l = _voting_decide(slab[0], cand_l, *tot_l, feat_info, depth_ok,
                           num_mask, cat_allowed, cfg)
    res_r = _voting_decide(slab[1], cand_r, *tot_r, feat_info, depth_ok,
                           num_mask, cat_allowed, cfg)
    return res_l, res_r


def _bucket_sizes(n: int, cfg: GrowerConfig):
    """Power-of-two compaction bucket ladder covering [min_bucket, 2^⌈lg n⌉]."""
    n_pow = 1 << (n - 1).bit_length() if n > 1 else 1
    s = min(cfg.min_bucket, n_pow)
    sizes = [s]
    while s < n_pow:
        s *= 2
        sizes.append(s)
    return sizes


def _partition_switch(row_order, col, off, cnt, thr, use_cat, cat_bits,
                      n, sizes, cfg: GrowerConfig):
    """Partition the split leaf's contiguous ``row_order`` segment into
    left|right in place — LightGBM's ``DataPartition::Split`` re-shaped for
    static-shape jit.  The segment (dynamic offset, dynamic length ``cnt``)
    is sliced at the smallest power-of-two bucket that fits, partitioned
    with an in-bucket stable cumsum+scatter, and written back, so the cost
    is O(leaf size), not O(n).  ``lax.switch`` picks the bucket; only the
    chosen branch executes, and no collectives live inside branches (shards
    may pick different buckets under a data mesh).

    Returns ``(row_order', cnt_left, cnt_right)`` (counts of ALL leaf rows
    per side, bagged-out rows included — the partition tracks membership,
    histograms track contribution).  On the CPU backend the whole
    partition is one in-place native pass (ops/histogram.py
    native_partition).
    """
    if cfg.hist_method in ("auto", "native"):
        from ..ops.histogram import native_partition
        res = native_partition(row_order, col, off, cnt, thr, use_cat,
                               cat_bits, cfg.num_bins)
        if res is not None:
            return res

    def make(size):
        def fn(_):
            seg = jax.lax.dynamic_slice(row_order, (off,), (size,))
            iota = jnp.arange(size, dtype=jnp.int32)
            valid = iota < cnt
            rows = jnp.minimum(seg, n - 1)
            cseg = jnp.take(col, rows).astype(jnp.int32)
            if cfg.use_categorical:
                gl = jnp.where(use_cat, bin_in_bitset(cat_bits, cseg),
                               cseg <= thr)
            else:
                gl = cseg <= thr
            go_l = valid & gl
            go_r = valid & ~gl
            cnt_r = jnp.sum(go_r, dtype=jnp.int32)
            cnt_l = cnt - cnt_r
            pos_l = jnp.cumsum(go_l.astype(jnp.int32)) - 1
            pos_r = cnt_l + jnp.cumsum(go_r.astype(jnp.int32)) - 1
            # each leaf row gets a unique slot in [0, cnt); the bucket tail
            # (other leaves / sentinels) keeps its original values
            tgt = jnp.where(go_l, pos_l, jnp.where(go_r, pos_r, size))
            new_seg = seg.at[tgt].set(seg, mode="drop")
            out = jax.lax.dynamic_update_slice(row_order, new_seg, (off,))
            return out, cnt_l, cnt_r
        return fn

    branch = jnp.searchsorted(jnp.asarray(sizes, jnp.int32), cnt,
                              side="left")
    return jax.lax.switch(branch, [make(s) for s in sizes], 0)


def pack_bins_u32(bins: jnp.ndarray) -> jnp.ndarray:
    """(n, f) uint8 bins → (n, ceil(f/4)) uint32, four bins per word
    (little-endian within the word).  O(n·f) elementwise — cheap next to
    one histogram pass; computed once per tree, outside the split loop."""
    n, f = bins.shape
    f4 = (f + 3) // 4
    bu = bins.astype(jnp.uint32)
    if f4 * 4 != f:
        bu = jnp.pad(bu, ((0, 0), (0, f4 * 4 - f)))
    bu = bu.reshape(n, f4, 4)
    return (bu[..., 0] | (bu[..., 1] << 8) | (bu[..., 2] << 16)
            | (bu[..., 3] << 24))


def _segment_hist(bins, gh, row_order, off, cnt, n, sizes,
                  cfg: GrowerConfig, bins_pk=None, binsT=None):
    """Histogram the contiguous ``row_order[off:off+cnt]`` segment via the
    smallest power-of-two bucket gather.  Local (no psum) — the caller
    reduces over the data axis, keeping collectives out of switch
    branches.  On the CPU backend the gather fuses into the native FFI
    kernel (no (size, f) materialization).  With ``bins_pk`` (see
    :func:`pack_bins_u32`) the row gather reads the packed words and the
    shift/mask unpack fuses into the histogram prologue.  With
    ``hist_method='pallas_fused'`` (and ``binsT`` provided) the row
    gather happens INSIDE the Pallas kernel against a VMEM-resident
    binsT block — no (size, f) sub-matrix ever touches HBM (PERF.md
    headroom item: the bucket-gather rivals the histogram itself)."""
    from ..ops.histogram import native_segment_hist
    if cfg.hist_method in ("auto", "native"):
        fused = native_segment_hist(bins, gh, row_order, off, cnt,
                                    cfg.num_bins,
                                    max_code=cfg.quantized_max_code)
        if fused is not None:
            return fused
    if (cfg.hist_method in ("pallas_fused", "pallas_ring")
            and binsT is not None and cfg.num_bins <= 256):
        from ..ops.pallas_histogram import (FUSED_MAX_ROWS,
                                            fused_compile_supported,
                                            histogram_pallas_fused)
        import jax as _jax
        interp = _jax.default_backend() not in ("tpu", "axon")
        # probe=False: this may run under trace, so only the CACHED
        # Mosaic verdict is consulted (the engine probes at config-build
        # time via resolve_histogram_method).  A known-bad verdict falls
        # through to the gather-then-pallas path below (ADVICE r5: the
        # fused method must not hard-fail on the hardware it targets).
        if (n <= FUSED_MAX_ROWS
                and fused_compile_supported(interp, probe=False)
                is not False):

            f_out = bins.shape[1]
            accum = ("int32" if jnp.issubdtype(gh.dtype, jnp.integer)
                     else "float32")

            def make_f(size):
                def fn(_):
                    seg = jax.lax.dynamic_slice(row_order, (off,), (size,))
                    valid = jnp.arange(size, dtype=jnp.int32) < cnt
                    rows = jnp.minimum(seg, n - 1)
                    gh_sub = jnp.take(gh, rows, axis=0) * \
                        valid.astype(gh.dtype)[:, None]
                    # binsT arrives pre-padded to the 8-feature fold
                    # (see _grow_tree_impl); slice back to real columns
                    return histogram_pallas_fused(
                        binsT, gh_sub, rows, cfg.num_bins, size,
                        accum=accum, interpret=interp)[:f_out]
                return fn

            branch = jnp.searchsorted(jnp.asarray(sizes, jnp.int32), cnt,
                                      side="left")
            return jax.lax.switch(branch, [make_f(s) for s in sizes], 0)
    f_cols = bins.shape[1]

    def make(size):
        def fn(_):
            seg = jax.lax.dynamic_slice(row_order, (off,), (size,))
            valid = jnp.arange(size, dtype=jnp.int32) < cnt
            rows = jnp.minimum(seg, n - 1)
            if bins_pk is not None:
                u = jnp.take(bins_pk, rows, axis=0)       # (size, f4) u32
                parts = jnp.stack(
                    [(u >> (8 * k)) & jnp.uint32(0xFF) for k in range(4)],
                    axis=-1)
                b_sub = parts.reshape(size, -1)[:, :f_cols] \
                    .astype(jnp.int32)
            else:
                b_sub = jnp.take(bins, rows, axis=0)
            gh_sub = jnp.take(gh, rows, axis=0) * \
                valid.astype(gh.dtype)[:, None]
            return compute_histogram(b_sub, gh_sub, cfg.num_bins,
                                     method=cfg.hist_method,
                                     max_code=cfg.quantized_max_code)
        return fn

    branch = jnp.searchsorted(jnp.asarray(sizes, jnp.int32), cnt,
                              side="left")
    return jax.lax.switch(branch, [make(s) for s in sizes], 0)


def _segment_hist_dist(bins, gh, row_order, off, cnt, n, sizes,
                       cfg: GrowerConfig, bins_pk=None, binsT=None):
    """Distributed segment histogram: returns ``(hist, reduced)`` where
    ``reduced`` is a STATIC bool — True when the cross-shard reduction
    already happened inside the kernel.

    With ``hist_method='pallas_ring'`` under a ring collective, the
    whole gather→histogram→ring-allreduce runs as ONE Pallas kernel
    (ops/pallas_collectives.fused_segment_hist_ring): the bucket is
    chosen from the GLOBAL max segment count (``pmax``) so every shard
    enters the same ``lax.switch`` branch — a collective may never live
    in a branch shards could disagree on — and the kernel overlaps the
    ICI transfer of finished histogram chunks with the MXU accumulation
    of the next.  Anything the static gates refuse falls back to the
    local :func:`_segment_hist` with the reduction applied by the
    caller."""
    use_fused_ring = (
        cfg.collective == "ring" and cfg.hist_method == "pallas_ring"
        and cfg.axis_name is not None and not _is_voting(cfg)
        and cfg.data_axis_size > 1 and binsT is not None
        and cfg.num_bins <= 256)
    if use_fused_ring:
        from ..ops.pallas_collectives import (fused_ring_applicable,
                                              fused_ring_compile_supported,
                                              fused_segment_hist_ring)
        import jax as _jax
        interp = _jax.default_backend() not in ("tpu", "axon")
        # probe=False: only the cached Mosaic verdict is consulted under
        # the trace (the engine probes at config-build time)
        if (fused_ring_applicable(binsT.shape[0], n, cfg.num_bins,
                                  cfg.data_axis_size)
                and fused_ring_compile_supported(interp, probe=False)
                is not False):
            f_out = bins.shape[1]
            cnt_g = jax.lax.pmax(cnt, cfg.axis_name)
            accum = ("int32" if jnp.issubdtype(gh.dtype, jnp.integer)
                     else "float32")

            def make_f(size):
                def fn(_):
                    seg = jax.lax.dynamic_slice(row_order, (off,), (size,))
                    valid = jnp.arange(size, dtype=jnp.int32) < cnt
                    rows = jnp.minimum(seg, n - 1)
                    gh_sub = jnp.take(gh, rows, axis=0) * \
                        valid.astype(gh.dtype)[:, None]
                    return fused_segment_hist_ring(
                        binsT, gh_sub, rows, cfg.num_bins, size,
                        cfg.axis_name, cfg.data_axis_size,
                        accum=accum, interpret=interp)[:f_out]
                return fn

            branch = jnp.searchsorted(jnp.asarray(sizes, jnp.int32),
                                      cnt_g, side="left")
            return jax.lax.switch(branch, [make_f(s) for s in sizes],
                                  0), True
    return _segment_hist(bins, gh, row_order, off, cnt, n, sizes, cfg,
                         bins_pk=bins_pk, binsT=binsT), False


def _leaf_of_position(leaf_start, leaf_cnt, n):
    """(n,) leaf id per row_order position, from the leaves' contiguous
    segments: scatter each non-empty leaf's id at its start position, then
    forward-fill with an associative last-set-wins scan."""
    idx = jnp.where(leaf_cnt > 0, leaf_start, n)   # empty leaves dropped
    k1 = jnp.full(n, -1, jnp.int32).at[idx].set(
        leaf_start.astype(jnp.int32), mode="drop")
    payload = jnp.zeros(n, jnp.int32).at[idx].set(
        jnp.arange(leaf_start.shape[0], dtype=jnp.int32), mode="drop")

    def comb(a, b):
        k1a, pa = a
        k1b, pb = b
        t = k1b >= k1a
        return jnp.where(t, k1b, k1a), jnp.where(t, pb, pa)

    _, leaf_of_p = jax.lax.associative_scan(comb, (k1, payload))
    return leaf_of_p


def _totals_from_hist(hist):
    """Leaf totals via any one feature's bins (they partition the rows)."""
    s = jnp.sum(hist[0], axis=0)             # (3,)
    return s[0], s[1], s[2]


def _global_totals(g, h, c, cfg: GrowerConfig):
    """Leaf totals are global quantities; under voting the histograms stay
    local, so the (3,) totals are psum-reduced explicitly."""
    if _is_voting(cfg):
        tot = jax.lax.psum(jnp.stack([g, h, c]), cfg.axis_name)
        return tot[0], tot[1], tot[2]
    return g, h, c


def _find_split(hist, pg, ph, pc, fi, depth_ok, cfg: GrowerConfig,
                deq=None):
    """Best split over ``hist``.  ``deq`` (quantized mode): ``hist`` is
    raw int32 codes; voting forwards it so the candidate slab crosses
    the wire low-bit, every other path dequantizes up front — the gain
    math is unchanged f32 by construction."""
    if _is_voting(cfg):
        return find_best_split_voting(hist, pg, ph, pc, fi, depth_ok, cfg,
                                      deq=deq)
    if deq is not None:
        hist = deq(hist)
    if (cfg.hist_method in ("auto", "native") and not cfg.use_categorical
            and cfg.axis_name is None and cfg.feature_axis_name is None
            and (cfg.min_sum_hessian_in_leaf > 0 or cfg.lambda_l2 > 0)):
        # serial CPU path: the whole FindBestThreshold scan as one FFI
        # call; the C++ pass picks the winner, the gain is recomputed on
        # XLA's float trajectory (see native_find_split).  Mesh/voting/
        # categorical keep XLA; so does the degenerate min_sum_hessian=
        # lambda_l2=0 config, whose empty-side gains go NaN and argmax
        # semantics would differ.
        from ..ops.histogram import native_find_split
        res = native_find_split(
            hist, pg, ph, pc, fi[:, 0], depth_ok,
            cfg.min_data_in_leaf, cfg.min_sum_hessian_in_leaf,
            cfg.lambda_l1, cfg.lambda_l2,
            max(cfg.min_gain_to_split, EPS_GAIN), cfg.num_bins)
        if res is not None:
            gain, feat, b = res
            return (gain, feat, b, jnp.asarray(0, jnp.int32),
                    jnp.zeros(cfg.cat_words, jnp.uint32))
    return find_best_split(hist, pg, ph, pc, fi, depth_ok, cfg)


def collective_schedule(cfg: GrowerConfig, f: int, *,
                        n_rows_local: int = 0,
                        feature_shards: int = 1) -> dict:
    """Static per-TREE accounting of the grower's cross-shard
    collectives — computed host-side from shapes so the engine can
    journal ``collective_count``/``collective_payload_bytes`` per boost
    chunk without touching the trace (ISSUE 16 tentpole d).

    ``count`` counts the payload-bearing launches: histogram reductions
    under a data axis (the voting path batches both children of a grow
    step into one, so count = num_leaves = root + L-1 steps), and
    split-column broadcasts under a feature axis.  ``payload_bytes``
    sums the logical bytes each shard hands to EVERY training
    collective, tiny aux ones included (vote allgathers, leaf totals,
    partition counts, the feature-parallel gain/feat/bin tuple).
    ``dense_payload_bytes`` is what the same tree pays on the dense
    data-parallel reduce path — L reduces of the full (f, B, 3) f32
    state — the denominator of the bench artifact's payload ratio.
    Serial fits return zero count/payload.

    Histogram-slab terms are priced at the RESOLVED wire itemsize
    (ISSUE 17 satellite — the old hardcoded ``* 4`` over-billed
    quantized slabs): ``cfg.quantized_wire`` int8/int16 slabs cost 1/2
    bytes per element on the psum wire, while the ring transport always
    moves f32 lanes (``_ring_flat`` casts), so ring fits price 4
    regardless.  ``dense_payload_bytes`` stays f32-priced — it is the
    un-quantized denominator.  Quantized fits journal the per-tree grid
    scale ``pmax`` pair separately (``quantized_scale_bytes``): two
    scalar latency-bound launches, not slab payload.
    """
    B, L, W = cfg.num_bins, cfg.num_leaves, cfg.cat_words
    dense = L * f * B * 3 * 4
    if cfg.collective == "ring":
        itemsize = 4               # ring lanes are f32 (see _ring_flat)
    else:
        itemsize = {"int8": 1, "int16": 2}.get(cfg.quantized_wire, 4)
    count, payload, scale_bytes = 0, 0, 0
    if cfg.axis_name is not None and cfg.data_axis_size > 1:
        if _is_voting(cfg):
            k = min(cfg.voting_k, f)
            k2 = min(2 * k, f)
            slab = k2 * B * 3 * itemsize
            count += L
            payload += slab + (L - 1) * 2 * slab   # root + batched pairs
            payload += 4 * (k + (L - 1) * 2 * k)   # vote allgathers (i32)
            payload += L * 3 * 4                   # leaf-totals psums
        else:
            count += L                             # root + L-1 children
            payload += L * f * B * 3 * itemsize
        if _is_quantized(cfg):
            scale_bytes = 2 * 4                    # grid-scale pmax pair
        if cfg.compact_rows:
            # partition-count pairs ride the wire width too (they go
            # through _wire_cast_psum even on ring fits): counts are
            # bounded by n, which any resolved narrow wire admits
            cnt_item = {"int8": 1, "int16": 2}.get(cfg.quantized_wire, 4)
            payload += (L - 1) * 2 * cnt_item
    if cfg.feature_axis_name is not None and feature_shards > 1:
        count += L - 1                             # split-column psums
        payload += (L - 1) * n_rows_local * 4
        payload += (2 * L - 1) * (16 + W * 4)      # split-tuple allgathers
    return {"count": count, "payload_bytes": payload,
            "dense_payload_bytes": dense,
            "quantized_scale_bytes": scale_bytes}


@functools.partial(jax.jit, static_argnames=("cfg",))
def grow_tree(bins: jnp.ndarray, gh: jnp.ndarray,
              feat_info: jnp.ndarray,
              cfg: GrowerConfig,
              efb: Optional[EFBArrays] = None,
              binsT: Optional[jnp.ndarray] = None
              ) -> Tuple[TreeArrays, jnp.ndarray]:
    """Grow one tree.  ``gh``: (n, 3) masked (grad, hess, count);
    ``feat_info``: (f, 3) [mask, is_cat, n_value_bins] (see
    :func:`make_feat_info`); ``efb``: optional bundle maps — then
    ``bins`` holds bundle columns (gbdt/efb.py); ``binsT``: optional
    precomputed ``bins.T`` (fit-invariant — pass it when calling in a
    loop)."""
    return _grow_tree_impl(bins, gh, feat_info, cfg, efb, binsT=binsT)


def make_feat_info(f: int, feature_mask=None, is_cat=None, nbins=None):
    """Assemble the (f, 3) feature-info array the grower consumes."""
    import numpy as np
    out = np.zeros((f, 3), np.float32)
    out[:, 0] = 1.0 if feature_mask is None else feature_mask
    if is_cat is not None:
        out[:, 1] = is_cat
    if nbins is not None:
        out[:, 2] = nbins
    return out


def _grow_tree_impl(bins, gh, feat_info, cfg: GrowerConfig, efb=None,
                    binsT=None):
    # debug-mode invariants (no-ops unless the calling program is
    # checkified): every training path funnels through here, so corrupt
    # bins / non-finite gradients are caught regardless of entry point
    from ..core import debug as _debug
    _debug.check_bins_in_range(bins, cfg.num_bins)
    _debug.check_finite("gradients/hessians", gh)
    # quantized-gradient mode (ISSUE 17): discretize this tree's gh to
    # integer grid codes ONCE; every histogram below accumulates exact
    # int32, the sibling subtraction is bit-exact in integers, and the
    # split evaluation dequantizes through ``deq`` so the gain math is
    # unchanged f32.
    qscale = None
    deq = None
    if _is_quantized(cfg):
        gh, qscale = _quantize_gh(gh, cfg)
        deq = lambda h: h.astype(jnp.float32) * qscale  # noqa: E731

    def tot_deq(g, h, c):
        if qscale is None:
            return g, h, c
        return (g.astype(jnp.float32) * qscale[0],
                h.astype(jnp.float32) * qscale[1],
                c.astype(jnp.float32))

    n = bins.shape[0]
    # under EFB bins holds G bundle columns; histograms, feat_info and
    # tree state stay per ORIGINAL feature
    f = efb.gather_idx.shape[0] if efb is not None else bins.shape[1]
    L = cfg.num_leaves
    W = cfg.cat_words
    sizes = _bucket_sizes(n, cfg)
    neg_inf = jnp.float32(-jnp.inf)
    # Transposed copy for split-column reads: a column of row-major (n, f)
    # is a stride-f gather (slow on TPU); a row of (f, n) is one contiguous
    # dynamic-slice.  It is loop-invariant across the whole FIT, not just
    # this tree — XLA does NOT hoist it out of scanned boost loops (a
    # 48 ms/tree transpose at bench scale on CPU), so the scan builders
    # precompute it once and pass it in; the default covers direct calls.
    if binsT is None:
        binsT = bins.T
    binsT_hist = binsT
    if cfg.hist_method in ("pallas_fused", "pallas_ring"):
        # pad the feature axis to the kernel's fold ONCE per grow — a
        # per-call jnp.pad inside the split loop would copy the whole
        # (f, n) matrix at every segment histogram.  The ring-fused
        # kernel additionally needs one chunk of feature blocks per
        # device, so it pads to 8 * data_axis_size.
        mult = 8
        if (cfg.hist_method == "pallas_ring"
                and cfg.collective == "ring" and cfg.data_axis_size > 1):
            mult = 8 * cfg.data_axis_size
        fp8 = (-binsT.shape[0]) % mult
        if fp8:
            binsT_hist = jnp.pad(binsT, ((0, fp8), (0, 0)))
    bins_pk = None
    if (cfg.packed_gather and cfg.compact_rows
            and bins.dtype == jnp.uint8):
        bins_pk = pack_bins_u32(bins)

    hist0 = _hist(bins, gh, cfg, efb)
    g0, h0, c0 = _global_totals(*tot_deq(*_totals_from_hist(hist0)), cfg)
    depth0_ok = (cfg.max_depth <= 0) | (0 < cfg.max_depth)
    bg0, bf0, bb0, bc0, bits0 = _find_split(
        hist0, g0, h0, c0, feat_info, jnp.asarray(depth0_ok), cfg,
        deq=deq)

    tree = TreeArrays(
        node_feat=jnp.zeros(L - 1, jnp.int32),
        node_bin=jnp.zeros(L - 1, jnp.int32),
        node_left=jnp.zeros(L - 1, jnp.int32),
        node_right=jnp.zeros(L - 1, jnp.int32),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_weight=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        node_is_cat=jnp.zeros(L - 1, jnp.int32),
        node_cat_bits=jnp.zeros((L - 1, W), jnp.uint32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(
            _leaf_output(g0, h0, cfg)),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(h0),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(c0),
        num_leaves=jnp.asarray(1, jnp.int32),
    )
    if cfg.compact_rows:
        n_pow = sizes[-1]
        row_leaf0 = jnp.zeros(1, jnp.int32)
        row_order0 = jnp.concatenate([
            jnp.arange(n, dtype=jnp.int32),
            jnp.full(n_pow, n, jnp.int32)])
        leaf_start0 = jnp.zeros(L, jnp.int32)
        leaf_cnt0 = jnp.zeros(L, jnp.int32).at[0].set(n)
    else:
        row_leaf0 = jnp.zeros(n, jnp.int32)
        row_order0 = jnp.zeros(1, jnp.int32)
        leaf_start0 = jnp.zeros(L, jnp.int32)
        leaf_cnt0 = jnp.zeros(L, jnp.int32)
    state = _GrowState(
        row_leaf=row_leaf0,
        row_order=row_order0,
        leaf_start=leaf_start0,
        leaf_cnt=leaf_cnt0,
        leaf_hist=jnp.zeros((L, f, cfg.num_bins, 3), hist0.dtype
                            ).at[0].set(hist0),
        leaf_g=jnp.zeros(L, jnp.float32).at[0].set(g0),
        leaf_h=jnp.zeros(L, jnp.float32).at[0].set(h0),
        leaf_c=jnp.zeros(L, jnp.float32).at[0].set(c0),
        leaf_depth=jnp.zeros(L, jnp.int32),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_is_right=jnp.zeros(L, bool),
        best_gain=jnp.full(L, neg_inf).at[0].set(bg0),
        best_feat=jnp.zeros(L, jnp.int32).at[0].set(bf0),
        best_bin=jnp.zeros(L, jnp.int32).at[0].set(bb0),
        best_is_cat=jnp.zeros(L, jnp.int32).at[0].set(bc0),
        best_cat_bits=jnp.zeros((L, W), jnp.uint32).at[0].set(bits0),
        tree=tree,
    )

    def split_step(i, state: _GrowState) -> _GrowState:
        l = jnp.argmax(state.best_gain).astype(jnp.int32)
        gain = state.best_gain[l]
        do_split = gain > neg_inf

        # The step body runs UNCONDITIONALLY with its effects gated by
        # ``do_split`` (see the merge at the end) instead of under
        # ``lax.cond``: XLA materializes copies of the untouched carry
        # buffers at every cond join, and the (L, f, B, 3) leaf_hist made
        # that ~half the per-split cost at bench scale (PERF.md round 4).
        # Inactive steps neutralize themselves: the partition/histogram
        # run with cnt forced to 0 (identity permutation, empty segment),
        # and every state write merges through ``ds``.
        def do(state: _GrowState, ds) -> _GrowState:
            feat = state.best_feat[l]
            thr = state.best_bin[l]
            new_id = (i + 1).astype(jnp.int32)
            if cfg.feature_axis_name is not None:
                # feat is a GLOBAL index but bins holds this shard's feature
                # slice: the owning shard contributes the split column, the
                # psum broadcasts it (LightGBM feature-parallel's bitmap
                # broadcast, as an ICI collective).
                f_local = bins.shape[1]
                shard = jax.lax.axis_index(cfg.feature_axis_name)
                owner = feat // f_local
                lidx = feat - owner * f_local
                col_local = jnp.where(
                    owner == shard,
                    jnp.take(binsT, jnp.minimum(lidx, f_local - 1), axis=0)
                    .astype(jnp.int32),
                    0)
                col = jax.lax.psum(col_local, cfg.feature_axis_name)
            elif efb is not None:
                col = efb_feature_column(binsT, feat, efb, cfg.num_bins)
            else:
                col = jnp.take(binsT, feat, axis=0)

            if cfg.compact_rows:
                # LightGBM DataPartition: split the leaf's contiguous
                # row_order segment in place (O(leaf size)), then histogram
                # only the SMALLER child's segment (globally smaller under
                # a data mesh, so every shard histograms the same side and
                # the psum-reduced partials compose); sibling by
                # subtraction.
                off = state.leaf_start[l]
                cnt = jnp.where(ds, state.leaf_cnt[l], 0)
                use_cat = state.best_is_cat[l] > 0
                row_order, cnt_l_p, cnt_r_p = _partition_switch(
                    state.row_order, col, off, cnt, thr, use_cat,
                    state.best_cat_bits[l], n, sizes, cfg)
                if cfg.axis_name is not None:
                    # counts are bounded by n, which the quantized wire
                    # policy keeps within the wire dtype — ride it too
                    tot = _wire_cast_psum(jnp.stack([cnt_l_p, cnt_r_p]),
                                          cfg)
                    use_right = tot[1] <= tot[0]
                else:
                    use_right = cnt_r_p <= cnt_l_p
                child_off = jnp.where(use_right, off + cnt_l_p, off)
                child_cnt = jnp.where(use_right, cnt_r_p, cnt_l_p)
                hist_small, reduced = _segment_hist_dist(
                    bins, gh, row_order, child_off, child_cnt, n, sizes,
                    cfg, bins_pk=bins_pk, binsT=binsT_hist)
                if efb is not None:
                    # expansion is linear, so it commutes with the
                    # reduction — safe whether the fused ring already
                    # reduced or the psum below still will
                    hist_small = _efb_expand(hist_small, efb)
                if (not reduced and cfg.axis_name is not None
                        and not _is_voting(cfg)):
                    # voting keeps per-leaf histograms local; only voted
                    # candidate slices are reduced inside _find_split
                    hist_small = _reduce_hist(hist_small, cfg)
                parent_hist = state.leaf_hist[l]
                hist_r = jnp.where(use_right, hist_small,
                                   parent_hist - hist_small)
                hist_l = parent_hist - hist_r
                row_leaf = state.row_leaf
                leaf_start = state.leaf_start.at[new_id].set(off + cnt_l_p)
                leaf_cnt = state.leaf_cnt.at[l].set(cnt_l_p) \
                                         .at[new_id].set(cnt_r_p)
            else:
                in_leaf = (state.row_leaf == l) & ds
                if cfg.use_categorical:
                    go_left_val = jnp.where(
                        state.best_is_cat[l] > 0,
                        bin_in_bitset(state.best_cat_bits[l],
                                      col.astype(jnp.int32)),
                        col <= thr)
                    go_right = in_leaf & ~go_left_val
                else:
                    go_right = in_leaf & (col > thr)
                row_leaf = jnp.where(go_right, new_id, state.row_leaf)
                hist_r = _hist(bins, gh * go_right[:, None], cfg, efb)
                hist_l = state.leaf_hist[l] - hist_r
                row_order = state.row_order
                leaf_start = state.leaf_start
                leaf_cnt = state.leaf_cnt
            g_r, h_r, c_r = _global_totals(
                *tot_deq(*_totals_from_hist(hist_r)), cfg)
            g_l = state.leaf_g[l] - g_r
            h_l = state.leaf_h[l] - h_r
            c_l = state.leaf_c[l] - c_r

            child_depth = state.leaf_depth[l] + 1
            depth_ok = jnp.asarray(
                (cfg.max_depth <= 0), bool) | (child_depth < cfg.max_depth)
            if _is_voting(cfg):
                # batched frontier (ISSUE 16): both children's votes
                # ride one allgather and both candidate slabs one
                # stacked reduction — 1 collective per grow step
                ((bg_l, bf_l, bb_l, bc_l, bits_l),
                 (bg_r, bf_r, bb_r, bc_r, bits_r)) = \
                    find_best_split_voting_pair(
                        hist_l, hist_r, (g_l, h_l, c_l),
                        (g_r, h_r, c_r), feat_info, depth_ok, cfg,
                        deq=deq)
            else:
                bg_l, bf_l, bb_l, bc_l, bits_l = _find_split(
                    hist_l, g_l, h_l, c_l, feat_info, depth_ok, cfg,
                    deq=deq)
                bg_r, bf_r, bb_r, bc_r, bits_r = _find_split(
                    hist_r, g_r, h_r, c_r, feat_info, depth_ok, cfg,
                    deq=deq)

            t = state.tree
            # link the new internal node into its parent
            p = state.leaf_parent[l]
            has_parent = p >= 0
            p_safe = jnp.maximum(p, 0)
            was_right = state.leaf_is_right[l]
            node_left = t.node_left.at[p_safe].set(
                jnp.where(has_parent & ~was_right, i, t.node_left[p_safe]))
            node_right = t.node_right.at[p_safe].set(
                jnp.where(has_parent & was_right, i, t.node_right[p_safe]))
            tree = t._replace(
                node_feat=t.node_feat.at[i].set(feat),
                node_bin=t.node_bin.at[i].set(thr),
                node_is_cat=t.node_is_cat.at[i].set(state.best_is_cat[l]),
                node_cat_bits=t.node_cat_bits.at[i].set(
                    state.best_cat_bits[l]),
                node_left=node_left.at[i].set(-(l + 1)),
                node_right=node_right.at[i].set(-(new_id + 1)),
                node_gain=t.node_gain.at[i].set(gain),
                node_value=t.node_value.at[i].set(
                    _leaf_output(state.leaf_g[l], state.leaf_h[l], cfg)),
                node_weight=t.node_weight.at[i].set(state.leaf_h[l]),
                node_count=t.node_count.at[i].set(state.leaf_c[l]),
                leaf_value=t.leaf_value
                    .at[l].set(_leaf_output(g_l, h_l, cfg))
                    .at[new_id].set(_leaf_output(g_r, h_r, cfg)),
                leaf_weight=t.leaf_weight.at[l].set(h_l).at[new_id].set(h_r),
                leaf_count=t.leaf_count.at[l].set(c_l).at[new_id].set(c_r),
                num_leaves=t.num_leaves + 1,
            )
            return _GrowState(
                row_leaf=row_leaf,
                row_order=row_order,
                leaf_start=leaf_start,
                leaf_cnt=leaf_cnt,
                # slice-gated: a full-buffer where() would re-traverse the
                # (L, f, B, 3) state — exactly the copy being avoided
                leaf_hist=state.leaf_hist
                    .at[l].set(jnp.where(ds, hist_l, state.leaf_hist[l]))
                    .at[new_id].set(jnp.where(ds, hist_r,
                                              state.leaf_hist[new_id])),
                leaf_g=state.leaf_g.at[l].set(g_l).at[new_id].set(g_r),
                leaf_h=state.leaf_h.at[l].set(h_l).at[new_id].set(h_r),
                leaf_c=state.leaf_c.at[l].set(c_l).at[new_id].set(c_r),
                leaf_depth=state.leaf_depth.at[l].set(child_depth)
                                           .at[new_id].set(child_depth),
                leaf_parent=state.leaf_parent.at[l].set(i)
                                             .at[new_id].set(i),
                leaf_is_right=state.leaf_is_right.at[l].set(False)
                                                 .at[new_id].set(True),
                best_gain=state.best_gain.at[l].set(bg_l)
                                         .at[new_id].set(bg_r),
                best_feat=state.best_feat.at[l].set(bf_l)
                                         .at[new_id].set(bf_r),
                best_bin=state.best_bin.at[l].set(bb_l)
                                       .at[new_id].set(bb_r),
                best_is_cat=state.best_is_cat.at[l].set(bc_l)
                                             .at[new_id].set(bc_r),
                best_cat_bits=state.best_cat_bits.at[l].set(bits_l)
                                                 .at[new_id].set(bits_r),
                tree=tree,
            )

        new_state = do(state, do_split)
        big = ("row_leaf", "row_order", "leaf_hist")
        merged = {}
        for name in _GrowState._fields:
            nv, ov = getattr(new_state, name), getattr(state, name)
            if name in big:   # self-neutralizing or slice-gated above
                merged[name] = nv
            else:             # L-sized (or smaller) — cheap full where
                merged[name] = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(do_split, a, b), nv, ov)
        return _GrowState(**merged)

    state = jax.lax.fori_loop(0, L - 1, split_step, state)
    if cfg.compact_rows:
        # reconstruct the per-row leaf assignment once per tree: position →
        # leaf from the segment table, then scatter through the permutation
        leaf_of_p = _leaf_of_position(state.leaf_start, state.leaf_cnt, n)
        row_leaf = jnp.zeros(n, jnp.int32).at[state.row_order[:n]].set(
            leaf_of_p)
        return state.tree, row_leaf
    return state.tree, state.row_leaf


def apply_shrinkage(tree: TreeArrays, learning_rate: float) -> TreeArrays:
    return tree._replace(
        leaf_value=tree.leaf_value * learning_rate,
        node_value=tree.node_value * learning_rate)


def _tree_walk(tree: TreeArrays, n: int, max_steps: int, get_val):
    """Shared depth-bounded tree walk: ``get_val(safe_node)`` supplies
    each row's current split-column bin (local gather, or a psum-
    assembled feature-sharded gather); everything else — threshold and
    categorical-bitset compares, next-node selection, the early-exit
    while_loop, leaf extraction — lives HERE once, so the local and
    feature-sharded walks cannot drift apart (their parity is
    test-pinned).

    The ``while_loop`` stops as soon as every row reached a leaf, so the
    walk costs O(actual tree depth) iterations — typically ~log2(L) —
    with ``max_steps`` (= num_leaves, the worst-case chain) only as the
    safety fuel.  (VERDICT r2 weak #7: the fixed O(L) walk hurt at
    numLeaves=255-class configs.)"""

    def step(node):
        is_leaf = node < 0
        safe = jnp.maximum(node, 0)
        val = get_val(safe)
        thr = tree.node_bin[safe]
        go_left = val <= thr
        # categorical nodes: left iff the row's bin is in the subset bitset
        words = jnp.take_along_axis(tree.node_cat_bits[safe],
                                    (val >> 5)[:, None], axis=1)[:, 0]
        left_cat = ((words >> (val & 31).astype(jnp.uint32)) & 1
                    ).astype(bool)
        go_left = jnp.where(tree.node_is_cat[safe] > 0, left_cat, go_left)
        nxt = jnp.where(go_left, tree.node_left[safe],
                        tree.node_right[safe])
        return jnp.where(is_leaf, node, nxt)

    def cond(state):
        node, fuel = state
        return (fuel > 0) & jnp.any(node >= 0)

    def body(state):
        node, fuel = state
        return step(node), fuel - 1

    start = jnp.where(tree.num_leaves > 1,
                      jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32))
    node, _ = jax.lax.while_loop(
        cond, body, (start, jnp.asarray(max_steps, jnp.int32)))
    leaf = -(node + 1)
    return tree.leaf_value[leaf]


@functools.partial(jax.jit, static_argnames=("max_steps",))
def predict_tree_binned(tree: TreeArrays, bins: jnp.ndarray,
                        max_steps: int) -> jnp.ndarray:
    """Score binned rows through one tree (validation sets, dart/goss
    score updates); all features local.  See :func:`_tree_walk`."""

    def get_val(safe):
        feat = tree.node_feat[safe]
        return jnp.take_along_axis(bins, feat[:, None], axis=1)[:, 0]

    return _tree_walk(tree, bins.shape[0], max_steps, get_val)


@functools.partial(jax.jit, static_argnames=("max_steps", "num_bins"))
def predict_tree_binned_efb(tree: TreeArrays, bins_b: jnp.ndarray,
                            max_steps: int, efb: EFBArrays,
                            num_bins: int) -> jnp.ndarray:
    """:func:`predict_tree_binned` over an EFB-BUNDLED matrix: node ids
    are ORIGINAL features, so each walk level decodes the row's bundle
    column back to the feature's bin (the per-row form of
    :func:`efb_feature_column`) before the compare — the piece that let
    goss/dart score on the bundled training matrix."""

    def get_val(safe):
        feat = tree.node_feat[safe]
        bcol = jnp.take_along_axis(
            bins_b, efb.bundle_of[feat][:, None],
            axis=1)[:, 0].astype(jnp.int32)
        off = efb.off_of[feat]
        nb = efb.nb_of[feat]
        raw = bcol - off
        inr = (raw >= 0) & (raw <= nb)
        return jnp.where(inr, jnp.where(raw == nb, num_bins - 1, raw),
                         efb.default_of[feat])

    return _tree_walk(tree, bins_b.shape[0], max_steps, get_val)


def predict_tree_binned_any(tree: TreeArrays, bins: jnp.ndarray,
                            max_steps: int, efb=None,
                            num_bins: int = 256) -> jnp.ndarray:
    """One call site for 'walk this matrix': plain per-feature bins when
    ``efb`` is None, EFB bundle decode otherwise.  Callers must pass the
    efb that matches THE MATRIX BEING WALKED — training matrices are
    bundled under EFB, validation matrices never are."""
    if efb is None:
        return predict_tree_binned(tree, bins, max_steps)
    return predict_tree_binned_efb(tree, bins, max_steps, efb, num_bins)


def predict_tree_binned_fshard(tree: TreeArrays, bins_local: jnp.ndarray,
                               max_steps: int,
                               axis_name: str) -> jnp.ndarray:
    """:func:`predict_tree_binned` with FEATURES sharded over
    ``axis_name`` (every shard holds all rows of its feature slice).

    Per walk step, the shard owning each row's current split column
    contributes that row's bin and one ``psum`` assembles the compare
    vector — the scoring-side analog of the grower's feature-parallel
    split-column broadcast (grower.py split_step).  The loop trip count
    is identical on every shard of the feature axis (they walk the same
    rows through the same replicated tree), so the in-loop collective is
    SPMD-safe; cost is one (n,) psum per tree level.
    """
    n, f_local = bins_local.shape
    shard = jax.lax.axis_index(axis_name)

    def get_val(safe):
        feat = tree.node_feat[safe]                 # GLOBAL feature ids
        owner = feat // f_local
        lidx = jnp.minimum(feat - owner * f_local, f_local - 1)
        val_local = jnp.where(
            owner == shard,
            jnp.take_along_axis(bins_local, lidx[:, None],
                                axis=1)[:, 0].astype(jnp.int32),
            0)
        return jax.lax.psum(val_local, axis_name)

    return _tree_walk(tree, n, max_steps, get_val)
