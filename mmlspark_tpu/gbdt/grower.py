"""Leaf-wise histogram tree grower, fully jit-compatible.

TPU-native replacement for LightGBM's ``SerialTreeLearner``/
``DataParallelTreeLearner`` (driven by the reference through
``LGBM_BoosterUpdateOneIter``; SURVEY.md §3.1 hot loop).  Design notes:

* **Static shapes.**  A tree has a fixed budget of ``num_leaves`` leaves and
  ``num_leaves - 1`` internal nodes; growth is a ``fori_loop`` of
  ``num_leaves - 1`` split steps with inactive steps masked out via
  ``lax.cond`` — XLA's answer to LightGBM's dynamic leaf queue.
* **Leaf membership as a vector.**  Instead of partitioned row indices, a
  ``row_leaf`` (n,) assignment vector selects the split leaf's rows by mask;
  leaf-conditional histograms are built from *masked* gradient triples so
  every step has identical shape and cost.
* **Histogram subtraction.**  Each split builds one child histogram and
  derives the sibling by subtraction, exactly like LightGBM.
* **Leaf numbering parity.**  Splitting leaf ``l`` at step ``i`` creates
  internal node ``i``; the left child keeps leaf id ``l`` and the right
  child becomes leaf ``i + 1`` — the same numbering LightGBM uses, so model
  export is a direct array dump.
* **Distributed.**  Pass ``axis_name`` when running under ``shard_map`` with
  rows sharded across the mesh: local histograms are ``psum``-reduced — the
  ICI-collective replacement for LightGBM's socket ``Network::Allreduce``
  (SURVEY.md §5.8).  Feature-axis sharding is layered on in
  :mod:`mmlspark_tpu.gbdt.distributed`.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.histogram import compute_histogram

EPS_GAIN = 1e-10


@dataclass(frozen=True)
class GrowerConfig:
    """Static hyper-parameters (hashable → usable as a jit static arg)."""
    num_leaves: int = 31
    max_depth: int = -1
    num_bins: int = 256
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    hist_method: str = "auto"
    axis_name: Optional[str] = None          # data-parallel psum axis
    feature_axis_name: Optional[str] = None  # feature-parallel axis


class TreeArrays(NamedTuple):
    """One grown tree.  Children encoding matches LightGBM: a child value
    ``c >= 0`` is an internal node index, ``c < 0`` is leaf ``~c``."""
    node_feat: jnp.ndarray    # (L-1,) i32
    node_bin: jnp.ndarray     # (L-1,) i32 threshold bin (<= goes left)
    node_left: jnp.ndarray    # (L-1,) i32
    node_right: jnp.ndarray   # (L-1,) i32
    node_gain: jnp.ndarray    # (L-1,) f32
    node_value: jnp.ndarray   # (L-1,) f32 internal output (shrinkage applied)
    node_weight: jnp.ndarray  # (L-1,) f32 sum of hessians
    node_count: jnp.ndarray   # (L-1,) f32 row count
    leaf_value: jnp.ndarray   # (L,) f32 (shrinkage applied)
    leaf_weight: jnp.ndarray  # (L,) f32
    leaf_count: jnp.ndarray   # (L,) f32
    num_leaves: jnp.ndarray   # () i32 actual leaves grown


class _GrowState(NamedTuple):
    row_leaf: jnp.ndarray     # (n,) i32
    leaf_hist: jnp.ndarray    # (L, f, B, 3)
    leaf_g: jnp.ndarray       # (L,)
    leaf_h: jnp.ndarray       # (L,)
    leaf_c: jnp.ndarray       # (L,)
    leaf_depth: jnp.ndarray   # (L,) i32
    leaf_parent: jnp.ndarray  # (L,) i32 (-1 for root)
    leaf_is_right: jnp.ndarray  # (L,) bool
    best_gain: jnp.ndarray    # (L,) f32 (-inf when leaf can't split)
    best_feat: jnp.ndarray    # (L,) i32
    best_bin: jnp.ndarray     # (L,) i32
    tree: TreeArrays


def _threshold_l1(g, l1):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_gain(g, h, cfg: GrowerConfig):
    t = _threshold_l1(g, cfg.lambda_l1)
    return jnp.square(t) / (h + cfg.lambda_l2)


def _leaf_output(g, h, cfg: GrowerConfig):
    t = _threshold_l1(g, cfg.lambda_l1)
    return -t / (h + cfg.lambda_l2)


def find_best_split(hist: jnp.ndarray, parent_g, parent_h, parent_c,
                    feature_mask: jnp.ndarray, depth_ok,
                    cfg: GrowerConfig) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                jnp.ndarray]:
    """Best (gain, feature, bin) over a (f, B, 3) histogram.

    Mirrors LightGBM's FindBestThreshold: left = bins <= b, validity by
    min_data_in_leaf / min_sum_hessian, gain = ΔL over the parent leaf.
    First-occurrence argmax reproduces LightGBM's ascending scan tie-break.
    """
    cum = jnp.cumsum(hist, axis=1)           # (f, B, 3)
    gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
    gr = parent_g - gl
    hr = parent_h - hl
    cr = parent_c - cl
    valid = ((cl >= cfg.min_data_in_leaf) & (cr >= cfg.min_data_in_leaf)
             & (hl >= cfg.min_sum_hessian_in_leaf)
             & (hr >= cfg.min_sum_hessian_in_leaf))
    # cannot split on the last bin (nothing to the right)
    valid = valid & (jnp.arange(hist.shape[1]) < hist.shape[1] - 1)[None, :]
    parent_gain = _leaf_gain(parent_g, parent_h, cfg)
    gains = (_leaf_gain(gl, hl, cfg) + _leaf_gain(gr, hr, cfg) - parent_gain)
    gains = jnp.where(valid & (feature_mask[:, None] > 0) & depth_ok,
                      gains, -jnp.inf)
    flat = gains.reshape(-1)
    idx = jnp.argmax(flat)
    best_gain = flat[idx]
    feat = (idx // hist.shape[1]).astype(jnp.int32)
    b = (idx % hist.shape[1]).astype(jnp.int32)
    if cfg.feature_axis_name is not None:
        # feature-parallel learner: each shard scanned its feature slice;
        # allgather candidate splits and pick the global winner
        # (LightGBM tree_learner=feature analog, SURVEY.md §2.3).
        ax = cfg.feature_axis_name
        gains_all = jax.lax.all_gather(best_gain, ax)       # (S,)
        feats_all = jax.lax.all_gather(feat, ax)
        bins_all = jax.lax.all_gather(b, ax)
        shard = jnp.argmax(gains_all)
        n_local = jnp.asarray(hist.shape[0], jnp.int32)
        best_gain = gains_all[shard]
        feat = feats_all[shard] + shard.astype(jnp.int32) * n_local
        b = bins_all[shard]
    gain_ok = best_gain > jnp.maximum(cfg.min_gain_to_split, EPS_GAIN)
    return jnp.where(gain_ok, best_gain, -jnp.inf), feat, b


def _hist(bins, gh, cfg: GrowerConfig):
    h = compute_histogram(bins, gh, cfg.num_bins, method=cfg.hist_method)
    if cfg.axis_name is not None:
        h = jax.lax.psum(h, cfg.axis_name)
    return h


def _totals_from_hist(hist):
    """Leaf totals via any one feature's bins (they partition the rows)."""
    s = jnp.sum(hist[0], axis=0)             # (3,)
    return s[0], s[1], s[2]


@functools.partial(jax.jit, static_argnames=("cfg",))
def grow_tree(bins: jnp.ndarray, gh: jnp.ndarray,
              feature_mask: jnp.ndarray,
              cfg: GrowerConfig) -> Tuple[TreeArrays, jnp.ndarray]:
    """Grow one tree.  ``gh``: (n, 3) masked (grad, hess, count)."""
    return _grow_tree_impl(bins, gh, feature_mask, cfg)


def _grow_tree_impl(bins, gh, feature_mask, cfg: GrowerConfig):
    n, f = bins.shape
    L = cfg.num_leaves
    neg_inf = jnp.float32(-jnp.inf)

    hist0 = _hist(bins, gh, cfg)
    g0, h0, c0 = _totals_from_hist(hist0)
    depth0_ok = (cfg.max_depth <= 0) | (0 < cfg.max_depth)
    bg0, bf0, bb0 = find_best_split(hist0, g0, h0, c0, feature_mask,
                                    jnp.asarray(depth0_ok), cfg)

    tree = TreeArrays(
        node_feat=jnp.zeros(L - 1, jnp.int32),
        node_bin=jnp.zeros(L - 1, jnp.int32),
        node_left=jnp.zeros(L - 1, jnp.int32),
        node_right=jnp.zeros(L - 1, jnp.int32),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_weight=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(
            _leaf_output(g0, h0, cfg)),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(h0),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(c0),
        num_leaves=jnp.asarray(1, jnp.int32),
    )
    state = _GrowState(
        row_leaf=jnp.zeros(n, jnp.int32),
        leaf_hist=jnp.zeros((L, f, cfg.num_bins, 3), jnp.float32
                            ).at[0].set(hist0),
        leaf_g=jnp.zeros(L, jnp.float32).at[0].set(g0),
        leaf_h=jnp.zeros(L, jnp.float32).at[0].set(h0),
        leaf_c=jnp.zeros(L, jnp.float32).at[0].set(c0),
        leaf_depth=jnp.zeros(L, jnp.int32),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_is_right=jnp.zeros(L, bool),
        best_gain=jnp.full(L, neg_inf).at[0].set(bg0),
        best_feat=jnp.zeros(L, jnp.int32).at[0].set(bf0),
        best_bin=jnp.zeros(L, jnp.int32).at[0].set(bb0),
        tree=tree,
    )

    def split_step(i, state: _GrowState) -> _GrowState:
        l = jnp.argmax(state.best_gain).astype(jnp.int32)
        gain = state.best_gain[l]
        do_split = gain > neg_inf

        def do(state: _GrowState) -> _GrowState:
            feat = state.best_feat[l]
            thr = state.best_bin[l]
            new_id = (i + 1).astype(jnp.int32)
            if cfg.feature_axis_name is not None:
                # feat is a GLOBAL index but bins holds this shard's feature
                # slice: the owning shard contributes the split column, the
                # psum broadcasts it (LightGBM feature-parallel's bitmap
                # broadcast, as an ICI collective).
                f_local = bins.shape[1]
                shard = jax.lax.axis_index(cfg.feature_axis_name)
                owner = feat // f_local
                lidx = feat - owner * f_local
                col_local = jnp.where(
                    owner == shard,
                    jnp.take(bins, jnp.minimum(lidx, f_local - 1), axis=1),
                    0)
                col = jax.lax.psum(col_local, cfg.feature_axis_name)
            else:
                col = jnp.take(bins, feat, axis=1)
            in_leaf = state.row_leaf == l
            go_right = in_leaf & (col > thr)
            row_leaf = jnp.where(go_right, new_id, state.row_leaf)

            hist_r = _hist(bins, gh * go_right[:, None], cfg)
            hist_l = state.leaf_hist[l] - hist_r
            g_r, h_r, c_r = _totals_from_hist(hist_r)
            g_l = state.leaf_g[l] - g_r
            h_l = state.leaf_h[l] - h_r
            c_l = state.leaf_c[l] - c_r

            child_depth = state.leaf_depth[l] + 1
            depth_ok = jnp.asarray(
                (cfg.max_depth <= 0), bool) | (child_depth < cfg.max_depth)
            bg_l, bf_l, bb_l = find_best_split(
                hist_l, g_l, h_l, c_l, feature_mask, depth_ok, cfg)
            bg_r, bf_r, bb_r = find_best_split(
                hist_r, g_r, h_r, c_r, feature_mask, depth_ok, cfg)

            t = state.tree
            # link the new internal node into its parent
            p = state.leaf_parent[l]
            has_parent = p >= 0
            p_safe = jnp.maximum(p, 0)
            was_right = state.leaf_is_right[l]
            node_left = t.node_left.at[p_safe].set(
                jnp.where(has_parent & ~was_right, i, t.node_left[p_safe]))
            node_right = t.node_right.at[p_safe].set(
                jnp.where(has_parent & was_right, i, t.node_right[p_safe]))
            tree = t._replace(
                node_feat=t.node_feat.at[i].set(feat),
                node_bin=t.node_bin.at[i].set(thr),
                node_left=node_left.at[i].set(-(l + 1)),
                node_right=node_right.at[i].set(-(new_id + 1)),
                node_gain=t.node_gain.at[i].set(gain),
                node_value=t.node_value.at[i].set(
                    _leaf_output(state.leaf_g[l], state.leaf_h[l], cfg)),
                node_weight=t.node_weight.at[i].set(state.leaf_h[l]),
                node_count=t.node_count.at[i].set(state.leaf_c[l]),
                leaf_value=t.leaf_value
                    .at[l].set(_leaf_output(g_l, h_l, cfg))
                    .at[new_id].set(_leaf_output(g_r, h_r, cfg)),
                leaf_weight=t.leaf_weight.at[l].set(h_l).at[new_id].set(h_r),
                leaf_count=t.leaf_count.at[l].set(c_l).at[new_id].set(c_r),
                num_leaves=t.num_leaves + 1,
            )
            return _GrowState(
                row_leaf=row_leaf,
                leaf_hist=state.leaf_hist.at[l].set(hist_l)
                                         .at[new_id].set(hist_r),
                leaf_g=state.leaf_g.at[l].set(g_l).at[new_id].set(g_r),
                leaf_h=state.leaf_h.at[l].set(h_l).at[new_id].set(h_r),
                leaf_c=state.leaf_c.at[l].set(c_l).at[new_id].set(c_r),
                leaf_depth=state.leaf_depth.at[l].set(child_depth)
                                           .at[new_id].set(child_depth),
                leaf_parent=state.leaf_parent.at[l].set(i)
                                             .at[new_id].set(i),
                leaf_is_right=state.leaf_is_right.at[l].set(False)
                                                 .at[new_id].set(True),
                best_gain=state.best_gain.at[l].set(bg_l)
                                         .at[new_id].set(bg_r),
                best_feat=state.best_feat.at[l].set(bf_l)
                                         .at[new_id].set(bf_r),
                best_bin=state.best_bin.at[l].set(bb_l)
                                       .at[new_id].set(bb_r),
                tree=tree,
            )

        return jax.lax.cond(do_split, do, lambda s: s, state)

    state = jax.lax.fori_loop(0, L - 1, split_step, state)
    return state.tree, state.row_leaf


def apply_shrinkage(tree: TreeArrays, learning_rate: float) -> TreeArrays:
    return tree._replace(
        leaf_value=tree.leaf_value * learning_rate,
        node_value=tree.node_value * learning_rate)


@functools.partial(jax.jit, static_argnames=("max_steps",))
def predict_tree_binned(tree: TreeArrays, bins: jnp.ndarray,
                        max_steps: int) -> jnp.ndarray:
    """Score binned rows through one tree (used for validation sets)."""
    n = bins.shape[0]

    def body(_, node):
        is_leaf = node < 0
        safe = jnp.maximum(node, 0)
        feat = tree.node_feat[safe]
        thr = tree.node_bin[safe]
        val = jnp.take_along_axis(
            bins, feat[:, None], axis=1)[:, 0]
        nxt = jnp.where(val <= thr, tree.node_left[safe],
                        tree.node_right[safe])
        return jnp.where(is_leaf, node, nxt)

    start = jnp.where(tree.num_leaves > 1,
                      jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32))
    node = jax.lax.fori_loop(0, max_steps, body, start)
    leaf = -(node + 1)
    return tree.leaf_value[leaf]
