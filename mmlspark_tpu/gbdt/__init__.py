from .classifier import LightGBMClassifier, LightGBMClassificationModel
from .regressor import LightGBMRegressor, LightGBMRegressionModel
from .ranking import LightGBMRanker, LightGBMRankerModel, ndcg_at_k
from .booster import Booster, HostTree
from .binning import BinMapper, fit_bin_mapper
from .engine import TrainParams, train, train_incremental
from .grower import GrowerConfig, TreeArrays, grow_tree
from .objectives import Objective, get_objective

__all__ = [
    "LightGBMClassifier", "LightGBMClassificationModel",
    "LightGBMRegressor", "LightGBMRegressionModel",
    "LightGBMRanker", "LightGBMRankerModel", "ndcg_at_k",
    "Booster", "HostTree", "BinMapper", "fit_bin_mapper",
    "TrainParams", "train", "train_incremental",
    "GrowerConfig", "TreeArrays", "grow_tree",
    "Objective", "get_objective",
]
