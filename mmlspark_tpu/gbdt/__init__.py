from .classifier import LightGBMClassifier, LightGBMClassificationModel
from .regressor import LightGBMRegressor, LightGBMRegressionModel
from .booster import Booster, HostTree
from .binning import BinMapper, fit_bin_mapper
from .engine import TrainParams, train
from .grower import GrowerConfig, TreeArrays, grow_tree
from .objectives import Objective, get_objective

__all__ = [
    "LightGBMClassifier", "LightGBMClassificationModel",
    "LightGBMRegressor", "LightGBMRegressionModel",
    "Booster", "HostTree", "BinMapper", "fit_bin_mapper",
    "TrainParams", "train", "GrowerConfig", "TreeArrays", "grow_tree",
    "Objective", "get_objective",
]
