"""Fit-time HBM budget for GBDT training (BASELINE config 5 scale guard).

The reference streams rows through LightGBM's C++ histogram pools and can
page; an XLA program cannot — every array in the jitted boost step must
fit HBM simultaneously, so a Criteo-class configuration (numLeaves=255,
maxBin=255, tens of millions of rows) must be budgeted BEFORE the first
compile, not discovered as a device OOM after minutes of tracing.
(Reference expected paths: LightGBM histogram pool sizing in
src/treelearner/serial_tree_learner.cpp, UNVERIFIED; SURVEY.md §7.)

The model below counts the resident arrays of one device's shard for the
dominant training path (the DataPartition grower inside the chunked
scan), plus the largest transient the bucket-ladder compaction
materializes.  It deliberately over-counts slightly (gradients and their
gh-stack both appear) — a guard that errs a few percent high beats an
OOM at iteration 40.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np


def estimate_fit_bytes(n_local: int, num_features: int, num_bins: int,
                       num_leaves: int, num_class: int = 1,
                       chunk: int = 64, bin_itemsize: int = 1,
                       bagging: bool = False, n_val_local: int = 0,
                       min_bucket: int = 2048) -> Dict[str, int]:
    """Per-device resident-bytes breakdown for one training fit.

    ``n_local``: this device's row count (global rows / data-mesh size).
    Returns a dict of named costs plus ``"total"``.
    """
    n, f, B, L, K, C = (n_local, num_features, num_bins, num_leaves,
                        num_class, chunk)
    costs: Dict[str, int] = {}
    costs["bins"] = n * f * bin_itemsize
    # scores + labels + weights + real/bag mask + row_order
    costs["row_vectors"] = n * 4 * (K + 4)
    # grad/hess (n, K) each + the (n, 3) gh stack the grower consumes
    costs["gradients"] = n * 4 * (2 * K + 3)
    # per-leaf histogram state: (L, f, B, 3) f32
    costs["leaf_hist"] = L * f * B * 3 * 4
    # largest compaction bucket: one (2^ceil(lg n), f) bins gather plus
    # its (size, 3) gh gather — the transient peak of _segment_hist
    n_pow = 1 << (n - 1).bit_length() if n > 1 else 1
    bucket = max(min_bucket, n_pow)
    costs["bucket_transient"] = bucket * (f * bin_itemsize + 12)
    # stacked per-chunk trees (C*K trees x ~14 L-sized f32/i32 fields)
    costs["chunk_trees"] = C * K * L * 14 * 4
    if bagging:
        costs["bag_masks"] = C * n * 4
    if n_val_local:
        costs["validation"] = n_val_local * (f * bin_itemsize
                                             + 4 * K * (C + 1))
    costs["total"] = sum(costs.values())
    return costs


def device_capacity_bytes() -> Optional[int]:
    """This device's usable memory, or None when unknown.

    ``MMLSPARK_TPU_HBM_BYTES`` overrides (also how tests pin a tiny
    budget); TPU backends report ``bytes_limit`` via ``memory_stats``;
    CPU reports nothing and the guard stays advisory.
    """
    env = os.environ.get("MMLSPARK_TPU_HBM_BYTES")
    if env:
        return int(float(env))
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 - backend without memory_stats
        pass
    return None


def check_fit_budget(n_local: int, num_features: int, num_bins: int,
                     num_leaves: int, num_class: int = 1, chunk: int = 64,
                     bin_itemsize: int = 1, bagging: bool = False,
                     n_val_local: int = 0, data_shards: int = 1,
                     verbosity: int = 1) -> Dict[str, int]:
    """Estimate, log, and fail FAST when the fit cannot fit.

    Raises ``MemoryError`` with the breakdown and concrete remediations
    (more data shards, smaller maxBin/numLeaves) instead of letting XLA
    OOM after a long compile.  Returns the breakdown.
    """
    costs = estimate_fit_bytes(
        n_local, num_features, num_bins, num_leaves, num_class, chunk,
        bin_itemsize, bagging, n_val_local)
    cap = device_capacity_bytes()
    if verbosity > 0:
        import logging
        logging.getLogger("mmlspark_tpu.gbdt").info(
            "fit memory budget: %.2f GB/device estimated%s",
            costs["total"] / 1e9,
            "" if cap is None else f" of {cap / 1e9:.2f} GB available")
    if cap is not None and costs["total"] > cap:
        detail = ", ".join(f"{k}={v / 1e9:.2f}GB"
                           for k, v in costs.items() if k != "total")
        need_shards = int(np.ceil(costs["total"] / cap * data_shards))
        raise MemoryError(
            f"GBDT fit needs ~{costs['total'] / 1e9:.2f} GB per device "
            f"({detail}) but only {cap / 1e9:.2f} GB is available. "
            f"Remedies: shard rows over a larger data mesh (>= "
            f"{need_shards} shards at this scale), lower maxBin "
            f"(uint8 bins at <=255), lower numLeaves, or reduce "
            f"baggingFreq chunking. Set MMLSPARK_TPU_HBM_BYTES to "
            f"override the detected capacity.")
    return costs
