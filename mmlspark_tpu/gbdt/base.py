"""LightGBM-compatible estimator base.

TPU-native analog of the reference's ``LightGBMBase`` shared train()
orchestration (lightgbm/LightGBMBase.scala, expected path, UNVERIFIED;
SURVEY.md §3.1).  Where the reference coalesces partitions to one task per
executor, runs a socket rendezvous and boots the native engine per executor,
this estimator bins features on host, ships the binned matrix to the device
mesh, and runs the jitted boosting loop (:mod:`mmlspark_tpu.gbdt.engine`).

Param names mirror the reference's public API (numIterations, learningRate,
numLeaves, …) so existing mmlspark code ports unchanged.  Cluster-shaped
params that have no TPU meaning (``useBarrierExecutionMode``, ``numTasks``,
``numThreads``) are accepted and recorded but do not affect execution.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.params import (Param, Params, TypeConverters, HasFeaturesCol,
                           HasLabelCol, HasPredictionCol, HasWeightCol,
                           HasValidationIndicatorCol)
from ..core.pipeline import Estimator, Model
from ..core.schema import DataTable, features_matrix
from ..core import serialize
from .binning import fit_bin_mapper
from .booster import Booster
from .engine import TrainParams, train
from .objectives import get_objective


class LightGBMParams(HasFeaturesCol, HasLabelCol, HasPredictionCol,
                     HasWeightCol, HasValidationIndicatorCol):
    """Shared LightGBM params — names track the reference's LightGBMParams."""

    numIterations = Param("numIterations", "Number of boosting iterations",
                          default=100, typeConverter=TypeConverters.toInt)
    learningRate = Param("learningRate", "Shrinkage rate", default=0.1,
                         typeConverter=TypeConverters.toFloat)
    numLeaves = Param("numLeaves", "Max leaves per tree", default=31,
                      typeConverter=TypeConverters.toInt)
    maxDepth = Param("maxDepth", "Max tree depth (<=0 means no limit)",
                     default=-1, typeConverter=TypeConverters.toInt)
    maxBin = Param("maxBin", "Max number of feature bins", default=255,
                   typeConverter=TypeConverters.toInt)
    lambdaL1 = Param("lambdaL1", "L1 regularization", default=0.0,
                     typeConverter=TypeConverters.toFloat)
    lambdaL2 = Param("lambdaL2", "L2 regularization", default=0.0,
                     typeConverter=TypeConverters.toFloat)
    minSumHessianInLeaf = Param("minSumHessianInLeaf",
                                "Minimal sum of hessians in one leaf",
                                default=1e-3,
                                typeConverter=TypeConverters.toFloat)
    minDataInLeaf = Param("minDataInLeaf",
                          "Minimal number of rows in one leaf", default=20,
                          typeConverter=TypeConverters.toInt)
    minGainToSplit = Param("minGainToSplit", "Minimal split gain", default=0.0,
                           typeConverter=TypeConverters.toFloat)
    baggingFraction = Param("baggingFraction", "Row subsample fraction",
                            default=1.0, typeConverter=TypeConverters.toFloat)
    baggingFreq = Param("baggingFreq",
                        "Resample rows every k iterations (0 disables)",
                        default=0, typeConverter=TypeConverters.toInt)
    baggingSeed = Param("baggingSeed", "Bagging seed", default=3,
                        typeConverter=TypeConverters.toInt)
    featureFraction = Param("featureFraction",
                            "Feature subsample fraction per tree",
                            default=1.0, typeConverter=TypeConverters.toFloat)
    earlyStoppingRound = Param("earlyStoppingRound",
                               "Stop if validation metric doesn't improve "
                               "for this many rounds (0 disables)",
                               default=0, typeConverter=TypeConverters.toInt)
    boostFromAverage = Param("boostFromAverage",
                             "Start scores from the label average",
                             default=True, typeConverter=TypeConverters.toBool)
    verbosity = Param("verbosity", "Engine verbosity", default=1,
                      typeConverter=TypeConverters.toInt)
    objective = Param("objective", "Training objective", default="regression",
                      typeConverter=TypeConverters.toString)
    parallelism = Param("parallelism",
                        "Tree learner parallelism: serial, data, feature or "
                        "voting (mapped to mesh axes on TPU)",
                        default="data", typeConverter=TypeConverters.toString)
    autoMeshMinRows = Param(
        "autoMeshMinRows",
        "Minimum training rows before fit() auto-shards across all "
        "visible devices when no mesh is pinned; smaller fits train "
        "serially (the per-fit shard_map compile and collective "
        "overhead dwarfs any win on small data).  setMesh() always "
        "shards regardless of size; 0 restores unconditional "
        "auto-sharding.",
        default=65536, typeConverter=TypeConverters.toInt)
    useBarrierExecutionMode = Param(
        "useBarrierExecutionMode",
        "Accepted for API parity; TPU meshes are always gang-scheduled",
        default=False, typeConverter=TypeConverters.toBool)
    numTasks = Param("numTasks",
                     "Accepted for API parity; the mesh shape decides "
                     "task layout on TPU", default=0,
                     typeConverter=TypeConverters.toInt)
    numThreads = Param("numThreads", "Accepted for API parity", default=0,
                       typeConverter=TypeConverters.toInt)
    initScoreCol = Param("initScoreCol", "Column with per-row initial scores",
                         default=None, typeConverter=TypeConverters.toString)
    initModelPath = Param(
        "initModelPath",
        "Path to a saved native (LightGBM-text) model to CONTINUE "
        "training from: its margins seed the boosting scores and its "
        "trees prepend the fitted forest (LightGBM's init_model / "
        "keep_training_booster)", default="",
        typeConverter=TypeConverters.toString)
    checkpointDir = Param(
        "checkpointDir",
        "Directory for chunk-boundary training checkpoints: a killed "
        "fit re-run with the same settings resumes from the last "
        "completed chunk, bit-identically (empty disables)", default="",
        typeConverter=TypeConverters.toString)
    featuresShapCol = Param("featuresShapCol",
                            "Output column for SHAP values (empty disables)",
                            default="", typeConverter=TypeConverters.toString)
    seed = Param("seed", "Random seed", default=42,
                 typeConverter=TypeConverters.toInt)
    boostingType = Param("boostingType",
                         "gbdt (plain boosting), goss (gradient-based "
                         "one-side sampling), dart (dropout boosting) or "
                         "rf (random forest)", default="gbdt",
                         typeConverter=TypeConverters.toString)
    dropRate = Param("dropRate", "dart: per-tree dropout probability",
                     default=0.1, typeConverter=TypeConverters.toFloat)
    maxDrop = Param("maxDrop", "dart: max trees dropped per iteration",
                    default=50, typeConverter=TypeConverters.toInt)
    skipDrop = Param("skipDrop", "dart: probability of skipping dropout "
                     "for an iteration", default=0.5,
                     typeConverter=TypeConverters.toFloat)
    dropSeed = Param("dropSeed", "dart: dropout random seed", default=4,
                     typeConverter=TypeConverters.toInt)
    topRate = Param("topRate",
                    "GOSS: fraction of rows kept by largest gradient",
                    default=0.2, typeConverter=TypeConverters.toFloat)
    otherRate = Param("otherRate",
                      "GOSS: fraction of remaining rows sampled (amplified "
                      "by (1-topRate)/otherRate)", default=0.1,
                      typeConverter=TypeConverters.toFloat)
    histogramMethod = Param("histogramMethod",
                            "TPU histogram backend: auto, dot16, onehot, "
                            "segment, pallas, pallas_bf16, pallas_fused (segment "
                            "gather fused in-kernel), pallas_ring (gather + "
                            "histogram + cross-shard ring reduce in one "
                            "kernel)", default="auto",
                            typeConverter=TypeConverters.toString)
    collective = Param("collective",
                       "Cross-shard histogram reduction on mesh fits: "
                       "auto, psum (XLA all-reduce) or ring (Pallas "
                       "on-chip ring reduce-scatter/all-gather; "
                       "docs/collectives.md)", default="auto",
                       typeConverter=TypeConverters.toString)
    quantizedGrad = Param(
        "quantizedGrad",
        "Quantized-gradient training (LightGBM use_quantized_grad "
        "analog): 'off' keeps f32 gradients; '16'/'8' discretize (g,h) "
        "per boost round onto a seeded stochastically-rounded integer "
        "grid, accumulate histograms in int32 and cross shards in the "
        "narrowest wire dtype the row count admits "
        "(docs/collectives.md).  Gains still evaluate in f32.  "
        "gbdt/goss/rf only; dart and ranking fits fall back to f32",
        default="off", typeConverter=TypeConverters.toString)
    categoricalSlotIndexes = Param(
        "categoricalSlotIndexes",
        "Feature indexes treated as categorical (reference "
        "LightGBMParams.categoricalSlotIndexes)", default=None,
        typeConverter=TypeConverters.toListInt)
    categoricalSlotNames = Param(
        "categoricalSlotNames",
        "Feature names treated as categorical (resolved against the "
        "features column names)", default=None,
        typeConverter=TypeConverters.toListString)
    catSmooth = Param("catSmooth", "Categorical smoothing (cat_smooth)",
                      default=10.0, typeConverter=TypeConverters.toFloat)
    catL2 = Param("catL2", "Extra L2 for categorical splits (cat_l2)",
                  default=10.0, typeConverter=TypeConverters.toFloat)
    maxCatThreshold = Param(
        "maxCatThreshold", "Max categories on the smaller split side",
        default=32, typeConverter=TypeConverters.toInt)
    maxCatToOnehot = Param(
        "maxCatToOnehot", "Cardinality at or below which one-vs-rest "
        "splits are used", default=4, typeConverter=TypeConverters.toInt)
    faultTolerantRetries = Param(
        "faultTolerantRetries",
        "Chunk-level training failure recovery: snapshot boosting state "
        "at chunk boundaries and replay a failed chunk up to this many "
        "times (0 disables; SURVEY.md section 5.3 analog of executor "
        "gang-restart)", default=0, typeConverter=TypeConverters.toInt)
    topK = Param("topK",
                 "voting parallelism (PV-Tree): features each worker "
                 "votes per split (reference LightGBMParams.topK)",
                 default=20, typeConverter=TypeConverters.toInt)
    enableBundle = Param(
        "enableBundle",
        "Exclusive Feature Bundling (LightGBM enable_bundle): merge "
        "mutually-exclusive sparse features (one-hot blocks) into single "
        "bundle columns so histogram work scales with bundles, not "
        "features.  Off by default; serial gbdt/rf/multiclass only",
        default=False, typeConverter=TypeConverters.toBool)
    maxConflictRate = Param(
        "maxConflictRate",
        "EFB conflict budget (LightGBM max_conflict_rate): fraction of "
        "rows allowed to violate exclusivity inside one bundle",
        default=0.0, typeConverter=TypeConverters.toFloat)
    passThroughArgs = Param("passThroughArgs",
                            "Raw 'key=value key=value' LightGBM param string "
                            "recorded into the model file",
                            default="", typeConverter=TypeConverters.toString)
    profileTraceDir = Param(
        "profileTraceDir",
        "Directory for a jax.profiler device trace of the whole fit "
        "(empty disables).  Perfetto/TensorBoard-readable; "
        "core.profiling.summarize_trace parses it offline — the "
        "TPU-native replacement for the reference's Spark-UI stage "
        "timings (SURVEY.md section 5.1)",
        default="", typeConverter=TypeConverters.toString)

    def _train_params(self) -> TrainParams:
        pass_through = {}
        for tok in self.getPassThroughArgs().split():
            if "=" in tok:
                k, _, v = tok.partition("=")
                pass_through[k] = v
        return TrainParams(
            num_iterations=self.getNumIterations(),
            learning_rate=self.getLearningRate(),
            num_leaves=self.getNumLeaves(),
            max_depth=self.getMaxDepth(),
            max_bin=self.getMaxBin(),
            lambda_l1=self.getLambdaL1(),
            lambda_l2=self.getLambdaL2(),
            min_data_in_leaf=self.getMinDataInLeaf(),
            min_sum_hessian_in_leaf=self.getMinSumHessianInLeaf(),
            min_gain_to_split=self.getMinGainToSplit(),
            bagging_fraction=self.getBaggingFraction(),
            bagging_freq=self.getBaggingFreq(),
            feature_fraction=self.getFeatureFraction(),
            early_stopping_round=self.getEarlyStoppingRound(),
            boost_from_average=self.getBoostFromAverage(),
            seed=self.getSeed(),
            bagging_seed=self.getBaggingSeed(),
            boosting=self.getBoostingType(),
            top_rate=self.getTopRate(),
            other_rate=self.getOtherRate(),
            drop_rate=self.getDropRate(),
            max_drop=self.getMaxDrop(),
            skip_drop=self.getSkipDrop(),
            drop_seed=self.getDropSeed(),
            histogram_method=self.getHistogramMethod(),
            collective=self.getCollective(),
            quantized_grad=self.getQuantizedGrad(),
            verbosity=self.getVerbosity(),
            parallelism=self.getParallelism(),
            top_k=self.getTopK(),
            fault_tolerant_retries=self.getFaultTolerantRetries(),
            checkpoint_dir=self.getOrDefault("checkpointDir"),
            enable_bundle=self.getEnableBundle(),
            max_conflict_rate=self.getMaxConflictRate(),
            cat_smooth=self.getCatSmooth(),
            cat_l2=self.getCatL2(),
            max_cat_threshold=self.getMaxCatThreshold(),
            max_cat_to_onehot=self.getMaxCatToOnehot(),
            pass_through=pass_through,
        )


class LightGBMBase(Estimator, LightGBMParams):
    """Shared fit() orchestration for classifier/regressor/ranker."""

    __abstractstage__ = True

    _default_objective = "regression"
    _mesh = None

    def setMesh(self, mesh) -> "LightGBMBase":
        """Pin an explicit ``(data, feature)`` device mesh for training."""
        self._mesh = mesh
        return self

    def _objective_kwargs(self) -> Dict:
        return {}

    def _prepare_labels(self, y: np.ndarray) -> np.ndarray:
        return np.asarray(y, np.float64)

    def _make_model(self, booster: Booster) -> "LightGBMModelBase":
        raise NotImplementedError

    def _grad_fn_override(self, table: DataTable, train_idx, y, w):
        return None

    def _ranking_info(self, table: DataTable, train_idx):
        """Structured query info for the mesh-sharded lambdarank path
        (rankers override; see engine._train_distributed_ranking)."""
        return None

    def _val_metric(self):
        return None

    def _val_metric_fn(self, table: DataTable, val_mask):
        """Validation metric (lower is better); default ignores the table.
        Rankers override this to capture validation query structure."""
        return self._val_metric()

    def _fit(self, table: DataTable) -> "LightGBMModelBase":
        X = features_matrix(table, self.getFeaturesCol())
        y = self._prepare_labels(table[self.getLabelCol()])
        n = X.shape[0]
        wcol = self.getWeightCol()
        w = np.asarray(table[wcol], np.float64) if wcol else None

        vcol = self.getValidationIndicatorCol()
        if vcol:
            val_mask = np.asarray(table[vcol]).astype(bool)
            train_idx = ~val_mask
        else:
            val_mask = None
            train_idx = np.ones(n, bool)

        obj_name = getattr(self, "_resolved_objective", None) \
            or self.getObjective() or self._default_objective
        num_class = getattr(self, "_num_class", 1)
        if obj_name in ("multiclass", "softmax", "multiclassova",
                        "ova") and num_class <= 1:
            num_class = int(np.max(y)) + 1
        objective = get_objective(obj_name, num_class=num_class,
                                  **self._objective_kwargs())

        feature_names = list(
            getattr(table[self.getFeaturesCol()], "columns", [])) or None
        cat_idx = list(self.getCategoricalSlotIndexes() or [])
        for nm in self.getCategoricalSlotNames() or []:
            if not feature_names or nm not in feature_names:
                raise ValueError(
                    f"categoricalSlotNames: {nm!r} not found among feature "
                    f"columns {feature_names}")
            cat_idx.append(feature_names.index(nm))
        cat_idx = sorted(set(cat_idx))
        # materialize the train slice once (val_mask is None on the common
        # no-validation path, where X IS the train set — two boolean
        # gathers of an 80 MB matrix cost ~1s of pure copying on one core)
        X_train = X if val_mask is None else X[train_idx]
        mapper = fit_bin_mapper(X_train, max_bin=self.getMaxBin(),
                                seed=self.getSeed(),
                                categorical_features=cat_idx or None)
        y_train = y[train_idx]
        w_train = w[train_idx] if w is not None else None
        iscol = self.getInitScoreCol()
        init_scores = (np.asarray(table[iscol], np.float64)[train_idx]
                       if iscol else None)
        has_val = val_mask is not None and val_mask.any()

        params = self._train_params()
        init_booster = None
        val_init_scores = None
        imp = self.getOrDefault("initModelPath")
        if imp:
            # Continued training (LightGBM init_model): boost from the
            # saved model's margins; its trees prepend the new forest.
            # Guard on the RESOLVED boosting type — passThroughArgs keys
            # naming TrainParams fields apply in __post_init__ and must
            # not bypass this check.
            if params.boosting in ("dart", "rf"):
                raise ValueError(
                    "initModelPath requires boostingType gbdt or goss: "
                    "dart re-weights (and rf averages) the WHOLE "
                    "ensemble, which is not additive over a frozen "
                    "prefix")
            init_booster = Booster.load_native_model(imp)
            if init_booster.num_class != \
                    objective.num_model_per_iteration:
                raise ValueError(
                    f"initModelPath model has num_class="
                    f"{init_booster.num_class}, this fit trains "
                    f"{objective.num_model_per_iteration}")
            if init_booster.max_feature_idx != X.shape[1] - 1:
                raise ValueError(
                    f"initModelPath model was trained on "
                    f"{init_booster.max_feature_idx + 1} features, "
                    f"this table has {X.shape[1]}")
            margins = np.asarray(init_booster.predict_margin(X_train),
                                 np.float64)
            init_scores = (margins if init_scores is None
                           else init_scores + margins)
            if has_val:
                # validation margins seed the val scores too (LightGBM's
                # init_model seeds valid sets): early stopping decides on
                # the MERGED model's trajectory, not the residual's
                val_init_scores = np.asarray(
                    init_booster.predict_margin(X[val_mask]), np.float64)
        ranking_info = self._ranking_info(table, train_idx)
        mesh = getattr(self, "_mesh", None)
        mesh_multi = mesh is not None and int(np.prod(
            [mesh.shape[a] for a in mesh.axis_names])) > 1
        if mesh_multi and ranking_info is not None:
            # the mesh lambdarank path consumes ranking_info directly;
            # don't build (and device-transfer) the serial gradient
            # closure just to discard it
            grad_override = None
        else:
            grad_override = self._grad_fn_override(table, train_idx,
                                                   y_train, w_train)
        # Distributed by default when a mesh is available, like the
        # reference trains across all executors (SURVEY.md §3.1); the
        # parallelism param picks the axis layout.
        # goss stays serial unless a mesh is pinned explicitly (per-shard
        # sampling is a semantic choice); dart is host-loop only.
        # Below autoMeshMinRows the fit stays serial: sharding a few
        # thousand rows buys nothing and pays a multi-second shard_map
        # compile plus per-iteration collectives.
        if mesh is None and grad_override is None and ranking_info is None \
                and self.getBoostingType() not in ("goss", "dart") \
                and len(y_train) >= self.getAutoMeshMinRows():
            import jax
            if jax.device_count() > 1:
                from .distributed import resolve_mesh
                mesh = resolve_mesh(self.getParallelism())

        bins = mapper.transform_packed(X_train)

        val_kwargs = {}
        if has_val:
            val_kwargs = dict(
                val_bins=mapper.transform_packed(X[val_mask]),
                val_labels=y[val_mask],
                val_weights=w[val_mask] if w is not None else None,
                val_metric=self._val_metric_fn(table, val_mask),
            )
            if val_init_scores is not None:
                val_kwargs["val_init_scores"] = val_init_scores
        from ..core.profiling import maybe_trace
        with maybe_trace(self.getProfileTraceDir()):
            booster = train(
                bins, y_train, w_train, mapper, objective, params,
                feature_names=feature_names,
                grad_fn_override=grad_override,
                mesh=mesh,
                init_scores=init_scores,
                ranking_info=ranking_info,
                **val_kwargs)
        if init_booster is not None:
            booster = init_booster.extended(booster)
        model = self._make_model(booster)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class LightGBMModelBase(Model, HasFeaturesCol, HasPredictionCol):
    """Shared scoring transformer; holds a :class:`Booster`."""

    __abstractstage__ = True

    featuresShapCol = Param("featuresShapCol",
                            "Output column for SHAP values (empty disables)",
                            default="", typeConverter=TypeConverters.toString)

    def __init__(self, booster: Optional[Booster] = None, **kwargs):
        super().__init__(**kwargs)
        self._booster = booster

    def getModel(self) -> Booster:
        """The underlying booster (mmlspark API parity)."""
        return self._booster

    def getNativeModel(self) -> str:
        return self._booster.save_native_model_string()

    def saveNativeModel(self, path: str, overwrite: bool = True) -> None:
        """Save in LightGBM text format, loadable by stock LightGBM.

        ``overwrite=False`` refuses to clobber an existing file, matching
        the reference's ``saveNativeModel(filename, overwrite)``
        (src/main/scala LightGBMClassifier.scala model save API).
        """
        import os
        if not overwrite and os.path.exists(path):
            raise FileExistsError(
                f"{path} exists and overwrite=False")
        self._booster.save_native_model(path)

    @classmethod
    def loadNativeModel(cls, path: str) -> "LightGBMModelBase":
        return cls(booster=Booster.load_native_model(path))

    @classmethod
    def loadNativeModelFromFile(cls, path: str) -> "LightGBMModelBase":
        """Reference-parity alias (LightGBMClassificationModel.
        loadNativeModelFromFile)."""
        return cls.loadNativeModel(path)

    @classmethod
    def loadNativeModelFromString(cls, model_str: str
                                  ) -> "LightGBMModelBase":
        """Reference-parity alias: parse a LightGBM model text blob."""
        return cls(booster=Booster.load_native_model_string(model_str))

    def _with_shap(self, table, X):
        """Append the featuresShapCol column (TreeSHAP contributions) when
        the param is set — reference featuresShapCol semantics."""
        col = self.getFeaturesShapCol()
        if not col:
            return table
        contribs = self._booster.predict_contrib(X)
        arr = np.empty(len(contribs), dtype=object)
        for i, row in enumerate(contribs):
            arr[i] = row
        return table.withColumn(col, arr)

    def getFeatureImportances(self, importance_type: str = "split"):
        return list(self._booster.feature_importances(importance_type))

    def _save_extra(self, path: str) -> None:
        import os
        with open(os.path.join(path, "model.lgb.txt"), "w") as f:
            f.write(self._booster.save_native_model_string())

    def _load_extra(self, path: str) -> None:
        import os
        self._booster = Booster.load_native_model(
            os.path.join(path, "model.lgb.txt"))
