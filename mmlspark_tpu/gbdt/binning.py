"""Quantile feature binning — the framework's BinMapper.

TPU-native analog of LightGBM's ``BinMapper``/``GreedyFindBin`` (invoked by
the reference through ``LGBM_DatasetCreateFromMat``; SURVEY.md §2.2, §3.1).
Continuous features are discretized into at most ``max_bin`` integer bins via
per-feature upper bounds:

* if a feature has ≤ ``max_bin`` distinct values, bounds are midpoints
  between consecutive distinct values (exact, LightGBM-style);
* otherwise bounds are weighted quantiles over a sample.

Missing values (NaN) map to a dedicated trailing bin, so split finding can
route them independently — the static-shape counterpart of LightGBM's
default-direction handling.  Binning runs on host numpy (it is a one-time
preprocessing pass, like the reference's executor-side dataset aggregation);
the binned ``uint8``/``int32`` matrix is what ships to the TPU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np


@dataclass
class BinMapper:
    """Per-feature binning spec: ``upper_bounds[f]`` sorted ascending.

    Categorical features (``categorical[f]``) bin by category identity
    instead: ``cat_values[f]`` lists the raw (non-negative integer) category
    per bin index, most-frequent first — the analog of LightGBM's
    categorical ``BinMapper`` (bin_type=categorical).  Unseen categories and
    NaN map to ``missing_bin``.
    """

    upper_bounds: List[np.ndarray]   # len f, each (num_bins_f - 1,) finite
    has_missing: np.ndarray          # (f,) bool
    num_total_bins: int              # B used for histogram sizing (max over f)
    missing_bin: int                 # index reserved for NaN (== B - 1)
    categorical: Optional[np.ndarray] = None   # (f,) bool
    cat_values: Optional[List[Optional[np.ndarray]]] = None  # raw cat per bin

    @property
    def num_features(self) -> int:
        return len(self.upper_bounds)

    @property
    def has_categorical(self) -> bool:
        return self.categorical is not None and bool(self.categorical.any())

    def is_categorical(self, j: int) -> bool:
        return self.categorical is not None and bool(self.categorical[j])

    def feature_num_bins(self, j: int) -> int:
        """Value bins actually used by feature j (excl. the missing bin)."""
        if self.is_categorical(j):
            return len(self.cat_values[j])
        return len(self.upper_bounds[j]) + 1

    @property
    def bin_dtype(self) -> np.dtype:
        """Narrowest integer dtype that holds every bin index (numpy dtype;
        jnp.asarray accepts it directly).  256 bins fit uint8 exactly — 4x
        less transfer/gather traffic than int32 in the training hot loop
        (grower gathers, histogram chunk reads)."""
        return np.dtype(np.uint8 if self.num_total_bins <= 256
                        else np.int32)

    def _fast_state(self, is64: bool):
        """Precomputed arrays for the native ``bin_columns`` kernel.

        For float32 inputs the float64 bounds are adjusted DOWN to the
        largest float32 ``c <= b``; then for every float32 value ``v``,
        ``c < v  ⇔  b < v`` (if ``c < v`` then ``v`` is a float32 above
        the largest float32 ≤ b, hence ``v > b``; conversely ``b < v``
        implies ``c ≤ b < v``), so uint8 bins from float32 comparisons
        match the float64 reference bit-exactly.  A uniform ``C``-cell
        grid per feature provides a starting hint; the kernel probes
        locally in both directions, so the hint only affects speed, never
        the result.  Features whose bounds pack > 32 deep into one cell
        (degenerate hint) use plain binary search instead.
        """
        key = "_fs64" if is64 else "_fs32"
        cached = getattr(self, key, None)
        if cached is not None:
            return cached
        f = self.num_features
        C = 2048
        nb = np.asarray([len(ub) for ub in self.upper_bounds], np.int32)
        m = max(int(nb.max()), 1) if f else 1
        dt = np.float64 if is64 else np.float32
        bext = np.full((f, m), np.inf, dt)
        lo = np.zeros(f, np.float32)
        scale = np.zeros(f, np.float32)
        base = np.zeros((f, C), np.int32)
        use_table = np.zeros(f, np.uint8)
        for j, ub in enumerate(self.upper_bounds):
            if len(ub) == 0 or self.is_categorical(j):
                continue
            if is64:
                c = ub
            else:
                c = ub.astype(np.float32)
                over = c.astype(np.float64) > ub
                c[over] = np.nextafter(c[over], np.float32(-np.inf))
            bext[j, :len(c)] = c
            span = float(c[-1]) - float(c[0])
            if len(c) >= 8 and span > 0 and np.isfinite(span):
                lo[j] = np.float32(c[0])
                with np.errstate(over="ignore"):
                    scale_j = np.float32(C / (span * (1 + 1e-6)))
                if not np.isfinite(scale_j):   # span below ~f32 tiny
                    continue
                scale[j] = scale_j
                edges = (float(lo[j])
                         + np.arange(C, dtype=np.float64) / float(scale[j]))
                b0 = np.searchsorted(c, edges.astype(c.dtype), side="left")
                top = np.searchsorted(
                    c, np.nextafter((edges + 1.0 / float(scale[j])
                                     ).astype(c.dtype), np.inf), side="left")
                if int((top - b0).max()) <= 32:
                    base[j] = b0
                    use_table[j] = 1
        state = (bext, nb, base, lo, scale, use_table)
        object.__setattr__(self, key, state)
        return state

    def transform_packed(self, X: np.ndarray) -> np.ndarray:
        """:meth:`transform` into the narrowest dtype via the native
        ``fastbin`` kernel (~0.2 s for the 400k×50 bench matrix vs ~3 s
        for numpy/torch searchsorted on this box's single core — the
        binning pass, not the TPU, was the round-2 fit bottleneck).  The
        uint8 output is what ships over the host↔device link: 4x fewer
        bytes than int32, which dominates fit startup on a tunneled TPU
        (~25-100 MB/s link; see BENCH_SWEEP.md).

        Shipping X and binning on-device loses: the raw f32 matrix is 4x
        the bytes of the binned u8 one, and the link is the bottleneck —
        measured 4-11s for 80 MB vs ~0.5s for the 20 MB binned form.

        Exactness: identical output to :meth:`transform` (float64
        semantics) for float32 and float64 inputs; pinned by
        tests/test_gbdt.py's packed-parity test.
        """
        dt = self.bin_dtype
        if dt != np.uint8 or X.dtype not in (np.float32, np.float64):
            # > 256 total bins (or exotic dtypes): torch's batched
            # searchsorted still beats the per-column numpy loop
            return self._transform_torch(X, dt)
        from .. import native
        if not native.bin_columns_available():
            return self._transform_torch(X, dt)
        is64 = X.dtype == np.float64
        bext, nb, base, lo, scale, use_table = self._fast_state(is64)
        Xc = np.ascontiguousarray(X)
        out = np.empty(X.shape, np.uint8)
        native.bin_columns(Xc, bext, nb, base, lo, scale, use_table,
                           self.missing_bin, out)
        if self.has_categorical:
            for j in np.nonzero(self.categorical)[0]:
                out[:, j] = self._transform_cat(X[:, j], int(j))
        return out

    def _transform_torch(self, X: np.ndarray, dt: np.dtype) -> np.ndarray:
        """Batched float64 searchsorted via torch — the fallback when the
        native kernel can't apply (non-uint8 bins, missing toolchain)."""
        if self.has_categorical:
            return self.transform(X).astype(dt)
        try:
            import torch
        except Exception:  # pragma: no cover - torch is baked into the image
            return self.transform(X).astype(dt)
        f = self.num_features
        maxlen = max((len(ub) for ub in self.upper_bounds), default=0)
        bounds = np.full((f, max(maxlen, 1)), np.inf, np.float64)
        for j, ub in enumerate(self.upper_bounds):
            bounds[j, :len(ub)] = ub
        Xt = torch.from_numpy(np.ascontiguousarray(X.T, dtype=np.float64))
        out = torch.searchsorted(torch.from_numpy(bounds), Xt, side="left")
        out = out.numpy().T.astype(dt)
        nan_mask = np.isnan(X)
        if nan_mask.any():
            out[nan_mask] = self.missing_bin
        return out

    def transform(self, X: np.ndarray) -> np.ndarray:
        """Map raw features to bin indices ``(n, f)``, NaN → missing_bin."""
        n, f = X.shape
        if f != self.num_features:
            raise ValueError(
                f"Expected {self.num_features} features, got {f}")
        out = np.empty((n, f), dtype=np.int32)
        for j in range(f):
            col = X[:, j]
            if self.is_categorical(j):
                out[:, j] = self._transform_cat(col, j)
                continue
            out[:, j] = np.searchsorted(self.upper_bounds[j], col, side="left")
            nan_mask = np.isnan(col)
            if nan_mask.any():
                out[nan_mask, j] = self.missing_bin
        return out

    def _transform_cat(self, col: np.ndarray, j: int) -> np.ndarray:
        cats = self.cat_values[j]                       # bin -> raw value
        order = np.argsort(cats)
        sorted_cats = cats[order]
        vals = np.nan_to_num(col, nan=-1.0).astype(np.int64)
        pos = np.searchsorted(sorted_cats, vals)
        pos = np.clip(pos, 0, len(sorted_cats) - 1)
        hit = sorted_cats[pos] == vals
        bins = np.where(hit, order[pos], self.missing_bin)
        return bins.astype(np.int32)

    def bin_threshold_value(self, feature: int, bin_idx: int) -> float:
        """Real-valued threshold for a split at ``bin <= bin_idx``.

        Matches LightGBM's convention of storing the bin upper bound in the
        model file, so exported models score identically on raw features.
        """
        ub = self.upper_bounds[feature]
        if bin_idx >= len(ub):
            # split isolating the top/missing bin: everything finite goes left
            return np.inf
        return float(ub[bin_idx])

    def feature_infos(self) -> List[str]:
        """LightGBM model-file ``feature_infos`` entries: [min:max] for
        numeric features, colon-joined category list for categorical."""
        infos = []
        for j, ub in enumerate(self.upper_bounds):
            if self.is_categorical(j):
                cats = np.sort(self.cat_values[j])
                infos.append(":".join(str(int(c)) for c in cats) or "none")
            elif len(ub) == 0:
                infos.append("none")
            else:
                infos.append(f"[{ub[0]:.6g}:{ub[-1]:.6g}]")
        return infos

    # -- serialization (ISSUE 18) -------------------------------------------

    def to_json(self) -> str:
        """Exact JSON round-trip of the bin ladder (ISSUE 18): the
        streaming-ingest spill and the refresh loop persist the ACTIVE
        model's mapper so binned uint8 segments stay interpretable
        across process death.  Bounds are float64 and Python's JSON
        float repr is shortest-round-trip, so
        ``from_json(m.to_json())`` reproduces every bound bit-exactly
        (binning, and therefore replay, is deterministic across the
        crash)."""
        import json
        doc = {
            "format": 1,
            "upper_bounds": [ub.tolist() for ub in self.upper_bounds],
            "has_missing": self.has_missing.astype(int).tolist(),
            "num_total_bins": int(self.num_total_bins),
            "missing_bin": int(self.missing_bin),
        }
        if self.categorical is not None:
            doc["categorical"] = self.categorical.astype(int).tolist()
            doc["cat_values"] = [
                None if cv is None else cv.tolist()
                for cv in (self.cat_values or [])]
        return json.dumps(doc, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "BinMapper":
        import json
        doc = json.loads(text)
        if doc.get("format") != 1:
            raise ValueError(
                f"unsupported BinMapper format {doc.get('format')!r}")
        cat = doc.get("categorical")
        return cls(
            upper_bounds=[np.asarray(ub, np.float64)
                          for ub in doc["upper_bounds"]],
            has_missing=np.asarray(doc["has_missing"], bool),
            num_total_bins=int(doc["num_total_bins"]),
            missing_bin=int(doc["missing_bin"]),
            categorical=None if cat is None else np.asarray(cat, bool),
            cat_values=None if cat is None else [
                None if cv is None else np.asarray(cv, np.float64)
                for cv in doc["cat_values"]])


def fit_bin_mapper(X: np.ndarray, max_bin: int = 255,
                   sample_cnt: int = 200000,
                   min_data_in_bin: int = 3,
                   seed: int = 0,
                   categorical_features: Optional[List[int]] = None
                   ) -> BinMapper:
    """Learn per-feature bin upper bounds (GreedyFindBin analog).

    ``max_bin`` counts value bins; one extra trailing bin is reserved for
    missing values, giving ``num_total_bins = max_bin + 1``.

    ``categorical_features``: column indexes binned by category identity
    (raw values must be non-negative integers, LightGBM's contract); the
    ``max_bin - 1`` most frequent categories get bins, the rest join the
    missing bin.
    """
    n, f = X.shape
    if n > sample_cnt:
        rng = np.random.default_rng(seed)
        idx = rng.choice(n, size=sample_cnt, replace=False)
        # sorted row gather: same sample set, sequential-ish memory access
        idx.sort()
        sample = X[idx]
    else:
        sample = X
    cat_set = set(int(c) for c in (categorical_features or []))
    for c in cat_set:
        if not 0 <= c < f:
            raise ValueError(
                f"categorical feature index {c} out of range [0, {f})")
    bounds: List[np.ndarray] = []
    has_missing = np.zeros(f, dtype=bool)
    categorical = np.zeros(f, dtype=bool)
    cat_values: List[Optional[np.ndarray]] = [None] * f
    for j in range(f):
        col = sample[:, j]
        nan = np.isnan(col)
        has_missing[j] = bool(nan.any())
        col = col[~nan]
        if j in cat_set:
            categorical[j] = True
            cat_values[j] = _find_categories(col, max_bin, j)
            bounds.append(np.empty(0, dtype=np.float64))
        else:
            bounds.append(_find_bounds(col, max_bin, min_data_in_bin))
    num_total_bins = max_bin + 1
    return BinMapper(upper_bounds=bounds, has_missing=has_missing,
                     num_total_bins=num_total_bins,
                     missing_bin=num_total_bins - 1,
                     categorical=categorical if cat_set else None,
                     cat_values=cat_values if cat_set else None)


def _find_categories(col: np.ndarray, max_bin: int, j: int) -> np.ndarray:
    if col.size and (col < 0).any():
        raise ValueError(
            f"Categorical feature {j} has negative values; categories must "
            "be non-negative integers (LightGBM contract)")
    ints = col.astype(np.int64)
    if col.size and not np.array_equal(ints, col):
        raise ValueError(
            f"Categorical feature {j} has non-integer values")
    vals, counts = np.unique(ints, return_counts=True)
    order = np.argsort(-counts, kind="stable")   # most frequent first
    return vals[order][:max_bin - 1].astype(np.int64)


def _find_bounds(col: np.ndarray, max_bin: int,
                 min_data_in_bin: int) -> np.ndarray:
    """One ``np.sort`` per column feeds BOTH the distinct-value census and
    the quantile cuts (``np.quantile``'s internal partition re-sorted every
    feature; on this box's single core that was ~40% of fit_bin_mapper).
    The quantile lerp reproduces ``np.quantile(..., method="linear")``
    bit-exactly, including its ``t >= 0.5`` rearrangement."""
    if col.size == 0:
        return np.empty(0, dtype=np.float64)
    s = np.sort(col)
    change = np.empty(s.size, bool)
    change[0] = True
    np.not_equal(s[1:], s[:-1], out=change[1:])
    starts = np.nonzero(change)[0]
    if starts.size <= 1:
        return np.empty(0, dtype=np.float64)
    if starts.size <= max_bin:
        # Exact: midpoints between consecutive distinct values, but respect
        # min_data_in_bin by merging tiny bins (LightGBM does the same).
        distinct = s[starts]
        counts = np.diff(np.append(starts, s.size))
        mids = (distinct[:-1] + distinct[1:]) / 2.0
        if min_data_in_bin > 1 and col.size >= 2 * min_data_in_bin:
            keep, acc = [], 0
            for i in range(len(mids)):
                acc += counts[i]
                if acc >= min_data_in_bin:
                    keep.append(mids[i])
                    acc = 0
            mids = np.asarray(keep, dtype=np.float64)
        return np.asarray(mids, dtype=np.float64)
    # Quantile spacing over the empirical distribution.
    qs = np.linspace(0, 1, max_bin + 1)[1:-1]
    pos = qs * (s.size - 1)
    lo = pos.astype(np.int64)
    frac = pos - lo
    a = s[lo]
    b = s[np.minimum(lo + 1, s.size - 1)]
    # np.quantile's _lerp: the diff stays in the COLUMN dtype, the lerp
    # itself promotes to float64 — fuzz-verified bit-exact for f32 and f64
    # columns (a pure-f64 lerp differs in the low bits on f32 columns)
    d = b - a
    cuts = np.where(frac >= 0.5, b - d * (1.0 - frac), a + d * frac)
    cuts = np.unique(cuts)
    return cuts.astype(np.float64)
