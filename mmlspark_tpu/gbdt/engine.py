"""Boosting loop — the executor-side training orchestration.

TPU-native analog of the reference's executor training loop
(``TrainUtils.trainLightGBM`` → ``LGBM_BoosterUpdateOneIter`` iterations;
SURVEY.md §3.1).  One jitted ``boost_step`` fuses grad/hess computation, tree
growth, and score update on device; the Python loop over iterations handles
bagging/feature-fraction re-sampling, validation metrics, and early stopping —
mirroring LightGBM's iteration loop on the host side of the JNI boundary,
minus the JNI.
"""

from __future__ import annotations

import functools
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import debug as _debug
from ..core import telemetry as _tm
from ..core.profiler import get_profiler, install_jax_hooks
from ..core.profiling import StageStats
from .binning import BinMapper, fit_bin_mapper
from .booster import Booster, HostTree, host_tree_from_arrays
from .grower import (EFBArrays, GrowerConfig, TreeArrays, apply_shrinkage,
                     collective_schedule, grow_tree, predict_tree_binned,
                     predict_tree_binned_any, predict_tree_binned_efb,
                     _grow_tree_impl)
from .objectives import Objective, MulticlassObjective


def _resolve_hist_method(method: str) -> str:
    """pallas_fused / pallas_ring compile-probe resolution, imported
    lazily: pallas (+ Mosaic) must not become an eager dependency of
    every gbdt import when the method is never requested.  The probe
    verdicts are cached process-wide per (backend, method), so repeated
    fits never re-probe (ops.pallas_histogram.probe_cached)."""
    if method not in ("pallas_fused", "pallas_ring"):
        return method
    from ..ops.pallas_histogram import resolve_histogram_method
    return resolve_histogram_method(method)


def _resolve_collective_cfg(params: "TrainParams", mesh, *,
                            ranking: bool = False):
    """Resolve ``params.collective`` → ``("psum"|"ring", mesh, reason)``.

    "auto" stays on psum until an on-chip A/B flips the default
    (tools/tpu_session.sh queues one).  "ring" requires a multi-shard
    layout whose data axis is the only populated one, on a path whose
    scans support the data-only mesh (gbdt/goss/rf/multiclass, data- or
    voting-parallel — not ranking, dart or a feature-sharded mesh), plus
    a Mosaic compile probe on accelerator backends; it degrades to psum
    with only a ``log.info``, and the downgrade REASON is returned so
    ``_record_fit_resolution`` lands it in ``last_fit_info`` and the
    /metrics info gauge (the third element is "none" when the request
    was honored or nothing beyond psum was asked for).  On success the
    mesh is rebuilt SINGLE-AXIS (``distributed.data_only_mesh``): the
    Pallas ring kernels — and their interpret-mode discharge, which
    rejects multi-axis environments — ring over exactly one named axis.
    Voting fits ride the same data-only mesh (their mesh layout is the
    data layout; the voted-column ring reduces only the candidate
    slab)."""
    if params.collective in ("auto", "psum", ""):
        return "psum", mesh, "none"
    if mesh is None:
        if params.collective == "ring":
            log.info("collective='ring' needs a multi-shard mesh; this "
                     "serial fit keeps psum (single_data_shard)")
            return "psum", mesh, "single_data_shard"
        return "psum", mesh, "none"
    if params.collective != "ring":
        raise ValueError(f"Unknown collective {params.collective!r}; "
                         "valid: auto, psum, ring")
    from ..core.mesh import DATA_AXIS
    from .distributed import _feat_n, data_only_mesh
    d = int(mesh.shape[DATA_AXIS])
    reason = ("single_data_shard" if d <= 1
              else "feature_axis" if _feat_n(mesh) > 1
              else "ranking" if ranking
              else "dart" if params.boosting == "dart"
              else None)
    if reason is not None:
        log.info("collective='ring' needs a multi-shard data-parallel "
                 "or voting gbdt/goss/rf fit; this fit keeps psum "
                 "(%s)", reason)
        return "psum", mesh, reason
    from ..ops.pallas_collectives import resolve_collective
    resolved = resolve_collective("ring", d)
    if resolved == "ring":
        return "ring", data_only_mesh(mesh), "none"
    return "psum", mesh, "compile_probe"


def _resolve_quantized(params: "TrainParams", n: int, mesh,
                       collective: str, *, ranking: bool = False):
    """Resolve ``params.quantized_grad`` → ``(bits, max_code, wire,
    collective, downgrade)`` (ISSUE 17).

    ``max_code`` is the per-round grid half-width: ``2^(bits-1)-1``
    clamped so ``n * max_code`` (the largest magnitude any int32
    histogram cell can reach — every row in one bin) keeps int32
    headroom.  ``wire`` is the dtype the psum slab crosses the
    interconnect in: the narrowest int that the SAME ``n * max_code``
    bound fits — int8/int16 when it already fits, else the grid is
    CLAMPED to make int16 fit when at least 3 code levels survive
    (payload beats resolution for histogram work; LightGBM's quantized
    training uses 2-5 bit grids), else int32.  Serial fits have no
    wire.  Paths the quantized grower doesn't support (dart's host
    rescale loop, lambdarank) and a ring whose f32 lane can't carry
    the codes exactly (``n * max_code >= 2^24``) degrade — quantization
    off or ring→psum respectively — with reason
    ``quantized_unsupported`` for ``last_fit_info`` and /metrics."""
    if params.quantized_grad == "off":
        return 0, 0, "none", collective, "none"
    if ranking or params.boosting == "dart":
        log.info("quantizedGrad=%s needs a gbdt/goss/rf fit (dart's "
                 "host loop and lambdarank keep f32 gradients); "
                 "quantization is off for this fit "
                 "(quantized_unsupported)", params.quantized_grad)
        return 0, 0, "none", collective, "quantized_unsupported"
    bits = int(params.quantized_grad)
    mc = min((1 << (bits - 1)) - 1, (2**31 - 1) // max(n, 1))
    from ..core.mesh import DATA_AXIS
    d = int(mesh.shape[DATA_AXIS]) if mesh is not None else 1
    if d <= 1:
        return bits, mc, "none", collective, "none"
    if n * mc <= 127:
        wire = "int8"
    elif n * mc <= 32767:
        wire = "int16"
    elif 32767 // max(n, 1) >= 3:
        mc = 32767 // n
        wire = "int16"
    else:
        wire = "int32"
    downgrade = "none"
    if collective == "ring" and n * mc >= (1 << 24):
        log.info("collective='ring' carries histograms in f32 lanes; "
                 "quantized codes up to n*max_code=%d cannot ride it "
                 "exactly — this fit keeps psum (quantized_unsupported)",
                 n * mc)
        collective, downgrade = "psum", "quantized_unsupported"
    return bits, mc, wire, collective, downgrade


#: What the LAST fit in this process actually ran (resolved histogram
#: kernel + collective + backend) — bench.py records it for provenance,
#: and the /metrics exposition below surfaces it as an info gauge.
last_fit_info: Dict[str, str] = {}


def _record_fit_resolution(cfg, collective: str,
                           downgrade: str = "none",
                           sched: Optional[dict] = None,
                           quantized_downgrade: str = "none") -> None:
    last_fit_info.clear()
    last_fit_info.update(histogram_method=cfg.hist_method,
                         collective=collective,
                         collective_downgrade=downgrade,
                         backend=jax.default_backend(),
                         quantized_bits=str(cfg.quantized_bits),
                         quantized_max_code=str(cfg.quantized_max_code),
                         quantized_wire=cfg.quantized_wire,
                         quantized_downgrade=quantized_downgrade)
    if sched is not None:
        # static per-tree collective accounting (grower.
        # collective_schedule) — bench.py folds these into the artifact
        # detail, and the info gauge exposes them as labels
        dense = max(1, sched["dense_payload_bytes"])
        last_fit_info.update(
            collective_count_per_tree=str(sched["count"]),
            collective_payload_bytes_per_tree=str(sched["payload_bytes"]),
            collective_payload_vs_dense=(
                f"{sched['payload_bytes'] / dense:.6f}"))
        if sched.get("quantized_scale_bytes"):
            last_fit_info.update(quantized_scale_bytes_per_tree=str(
                sched["quantized_scale_bytes"]))


def _collective_sched_for(cfg, mesh, n: int, f: int) -> dict:
    """Per-tree collective accounting for this fit: the grower schedule
    evaluated on the MESH-sharded cfg (axis names attach inside the
    scan builders, so the engine-level cfg alone would always read
    serial — zero count/payload)."""
    if mesh is None:
        return collective_schedule(cfg, f)
    from ..core.mesh import DATA_AXIS
    from .distributed import _feat_n, _sharded_cfg
    dn = int(mesh.shape[DATA_AXIS])
    return collective_schedule(
        _sharded_cfg(mesh, cfg), f,
        n_rows_local=-(-n // max(1, dn)),
        feature_shards=_feat_n(mesh))

log = logging.getLogger("mmlspark_tpu.gbdt")


@dataclass
class TrainParams:
    """Engine-level hyper-parameters (host-side; see LightGBMParams analog)."""
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = -1
    max_bin: int = 255
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    feature_fraction: float = 1.0
    early_stopping_round: int = 0
    boost_from_average: bool = True
    seed: int = 42
    bagging_seed: int = 3
    #: "gbdt", "goss" (gradient-based one-side sampling), "dart"
    #: (dropout-boosting, Rashmi & Gilad-Bachrach 2015), or "rf"
    #: (random forest: bagged unshrunk trees, averaged)
    boosting: str = "gbdt"
    top_rate: float = 0.2
    other_rate: float = 0.1
    #: dart knobs (LightGBM names/defaults)
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    drop_seed: int = 4
    #: mesh-axis layout ("serial"/"data"/"feature"/"data+feature"/"voting")
    parallelism: str = "data"
    #: PV-Tree voting: features voted per shard (LightGBM top_k)
    top_k: int = 20
    histogram_method: str = "auto"
    #: cross-shard histogram reduction on mesh fits: "auto" (psum until
    #: an on-chip A/B flips it), "psum", or "ring" — the Pallas on-chip
    #: ring reduce-scatter/all-gather (ops/pallas_collectives.py;
    #: docs/collectives.md).  Ring fits run on a data-only 1-axis mesh
    #: and degrade to psum wherever the kernel gates refuse.
    collective: str = "auto"
    #: pack four uint8 bins per u32 word for the per-split segment gather
    #: (grower.GrowerConfig.packed_gather); measured knob, default off
    packed_gather: bool = False
    #: quantized-gradient training (ISSUE 17; Shi et al. 2022, LightGBM
    #: use_quantized_grad): "off" keeps f32 gradients; "16"/"8"
    #: discretize (g, h) each boost round onto a seeded
    #: stochastically-rounded int grid, accumulate histograms in int32,
    #: and cross shards in the narrowest wire dtype the row count
    #: admits (``_resolve_quantized``).  Split gains dequantize back to
    #: f32, so the math of the gain formula is unchanged.
    quantized_grad: str = "off"
    verbosity: int = 1
    #: categorical split knobs (LightGBM names)
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    #: chunk-level failure recovery (SURVEY.md §5.3): > 0 snapshots the
    #: boosting state to host RAM at every chunk boundary and, when a
    #: chunk's device execution fails (preempted/lost chip, tunnel drop),
    #: re-uploads the inputs and replays THAT chunk up to this many times
    #: — the TPU-shaped analog of the reference's executor gang-restart.
    fault_tolerant_retries: int = 0
    #: Exclusive Feature Bundling (Ke et al. 2017; LightGBM
    #: enable_bundle): merge mutually-exclusive sparse features into
    #: bundle columns so histogram work scales with bundles, not
    #: features.  Serial gbdt/rf/multiclass paths only; trees and the
    #: exported model always reference original features.
    enable_bundle: bool = False
    max_conflict_rate: float = 0.0
    #: cross-process mid-fit checkpointing (SURVEY.md §5.3 elasticity):
    #: non-empty = a directory where the chunked scan loops persist
    #: (trees, scores, RNG streams, early-stopping state) at every chunk
    #: boundary; a killed fit re-run with the SAME inputs and params
    #: resumes from the last completed chunk bit-identically.  The
    #: snapshot is fingerprinted against (shape, params, topology) and
    #: ignored with a warning on mismatch; it is deleted on successful
    #: completion.  Live for the serial AND mesh gbdt/goss/rf/multiclass
    #: scan paths, including multicontroller sharded ingestion (each
    #: process persists its own score shards into the shared directory;
    #: see docs/fault-tolerance.md); inert (with a warning) for
    #: dart/ranking host loops.
    checkpoint_dir: str = ""
    #: chunk-boundary cadence when checkpointing: the scan chunk is
    #: bounded to this many iterations so at most this much work is
    #: lost to a process death.  Smaller = finer recovery granularity,
    #: more host syncs.  Chunking never changes the forest (the scan
    #: body is per-iteration), so this knob is excluded from the resume
    #: fingerprint.
    checkpoint_chunk: int = 32
    #: raw passthrough params recorded into the model file (parity with the
    #: reference's passThroughArgs).  Keys that NAME a TrainParams field
    #: are applied onto it (string-coerced) in ``__post_init__`` — like
    #: the reference, where passThroughArgs reach the native learner —
    #: while typed setters keep precedence semantics LightGBM-style
    #: (last writer wins: pass_through applies after the constructor).
    pass_through: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        for k, v in self.pass_through.items():
            if k == "pass_through" or not hasattr(self, k):
                continue
            cur = getattr(self, k)
            s = str(v).strip()
            try:
                if isinstance(cur, bool):
                    low = s.lower()
                    if low in ("1", "true", "yes", "on"):
                        val = True
                    elif low in ("0", "false", "no", "off"):
                        val = False
                    else:
                        raise ValueError(f"not a boolean: {s!r}")
                elif isinstance(cur, int):
                    val = int(s)
                elif isinstance(cur, float):
                    val = float(s)
                elif isinstance(cur, str):
                    val = s
                else:
                    continue
            except ValueError as e:
                raise ValueError(
                    f"passThroughArgs {k}={v!r} cannot be coerced to "
                    f"{type(cur).__name__}: {e}") from None
            setattr(self, k, val)
        qg = str(self.quantized_grad).strip().lower()
        self.quantized_grad = {"": "off", "0": "off", "false": "off",
                               "none": "off"}.get(qg, qg)
        if self.quantized_grad not in ("off", "8", "16"):
            raise ValueError(
                f"quantizedGrad={self.quantized_grad!r} is not supported; "
                "valid: off, 16, 8")


@functools.partial(jax.jit, static_argnames=("obj", "cfg", "lr"),
                   donate_argnums=(1,))
def _boost_step(bins, scores, labels, weights, bag_mask, feat_info,
                obj: Objective, cfg: GrowerConfig, lr: float):
    """One boosting iteration for a single tree (single-class)."""
    g, h = obj.grad_hess(scores, labels, weights)
    gh = jnp.stack([g * bag_mask, h * bag_mask, bag_mask], axis=1)
    tree, row_leaf = _grow_tree_impl(bins, gh, feat_info, cfg)
    scores = scores + lr * tree.leaf_value[row_leaf]
    tree = apply_shrinkage(tree, lr)
    return tree, scores


def _draw_feature_fraction(rng, fi_base: np.ndarray, f: int,
                           feature_fraction: float) -> np.ndarray:
    """One per-iteration featureFraction mask draw.  Every training path
    (serial, mesh, mesh-ranking) consumes the SAME rng stream through this
    helper, preserving the serial draw-order reproducibility contract."""
    k_keep = max(1, int(np.ceil(f * feature_fraction)))
    sel = rng.choice(f, size=k_keep, replace=False)
    fi_it = fi_base.copy()
    fi_it[:, 0] = 0.0
    fi_it[sel, 0] = 1.0
    return fi_it


def _dummy_val(K: int):
    return jnp.zeros((0,) if K == 1 else (0, K), jnp.float32)


# -- cross-process mid-fit checkpointing (TrainParams.checkpoint_dir) -------

_CKPT_FILE = "boost_checkpoint.npz"       # meta + loop state, atomic
#: one per tree chunk, write-once.  The index field is wide enough that a
#: Criteo-class fit (T up to 10^6 with chunk=1) never collides with the
#: clear glob, which is DERIVED from this template (``_ckpt_glob``), not
#: hand-maintained alongside it.
_CKPT_CHUNK = "boost_chunk_{:06d}.npz"
#: per-process mesh state, stamped with the boundary iteration so the
#: state write (first) and the meta write (last, process 0) are never
#: torn against each other: the meta's ``it`` names exactly the state
#: generation that was durable before it.  The prefix is a separate
#: constant so the per-process GC glob (prefix + ``*``) stays correct
#: if the iteration field is ever widened.
_CKPT_MESH_PREFIX = "mesh_state_p{:03d}_it"
_CKPT_MESH_STATE = _CKPT_MESH_PREFIX + "{:06d}.npz"

#: Process-wide training recovery observability (the training-side
#: analog of ``ScoringEngine.stats()``): cumulative counters over every
#: fit in this process, seeded to explicit zeros so "no recovery event
#: happened" is observable rather than a missing key.  Tests and the
#: chaos drill snapshot before/after a fit and assert deltas.
train_stats = StageStats()
for _k in ("chunks_replayed", "ckpt_saved", "ckpt_resumed",
           "ckpt_discarded", "boost_chunks", "ref_profiles",
           "collective_count", "collective_payload_bytes"):
    train_stats.incr(_k, 0)
del _k
# federate under the process registry: a serving process that also
# trains (or a training controller with a debug HTTP server) exposes
# these on /metrics next to the scoring stats (ISSUE 5)
_tm.get_registry().register("train", train_stats)
# compile-event attribution (ISSUE 12): jax is imported by this module,
# so the profiler's jax.monitoring listener can install here — every
# backend compile from now on lands in the compile ledger
install_jax_hooks()


def _fit_resolution_exposition() -> str:
    """Prometheus info gauge naming the RESOLVED histogram kernel and
    collective the last fit in this process ran — so /metrics answers
    "which kernel is training actually using" without log spelunking."""
    if not last_fit_info:
        return ""
    labels = ",".join(f'{k}="{v}"' for k, v in sorted(
        last_fit_info.items()))
    name = "mmlspark_tpu_train_histogram_method_info"
    return (f"# HELP {name} Resolved histogram kernel/collective of the "
            "last fit\n"
            f"# TYPE {name} gauge\n"
            f"{name}{{{labels}}} 1\n")


_tm.get_registry().register_exposition("train_histogram_method",
                                       _fit_resolution_exposition)


def _quantized_exposition() -> str:
    """Prometheus info gauge naming the quantized-gradient resolution of
    the last fit (ISSUE 17): grid bits, max code after headroom clamps,
    the wire dtype psum slabs cross shards in, and whether a downgrade
    fired — so /metrics answers "is training actually running low-bit,
    and how low" without log spelunking."""
    if not last_fit_info:
        return ""
    keys = ("quantized_bits", "quantized_max_code", "quantized_wire",
            "quantized_downgrade")
    labels = ",".join(
        f'{k[len("quantized_"):]}="{last_fit_info[k]}"'
        for k in keys if k in last_fit_info)
    if not labels:
        return ""
    name = "mmlspark_tpu_train_quantized_info"
    return (f"# HELP {name} Quantized-gradient resolution of the last "
            "fit\n"
            f"# TYPE {name} gauge\n"
            f"{name}{{{labels}}} 1\n")


_tm.get_registry().register_exposition("train_quantized",
                                       _quantized_exposition)


def _ckpt_event(name: str, **fields) -> None:
    """Journal a checkpoint lifecycle event, stamped with the current
    fit span so ``tools/trace_report.py`` can place it on the fit's
    timeline."""
    _tm.get_journal().emit(name, fit=_tm.current_fit_span(), **fields)


#: cap on rows fetched to the host per chunk boundary for the telemetry
#: train-loss gauge; larger fits are sampled with a stride (a gauge
#: needs a stable estimate, not the exact sum)
_MONITOR_LOSS_MAX_ROWS = 65536


def _monitor_chunk(it0: int, it1: int, dt_s: float, n_rows: int, K: int,
                   hist_method: str, objective=None, scores=None,
                   labels=None, weights=None,
                   collective: str = "none",
                   coll_sched: Optional[dict] = None) -> None:
    """Per-boost-chunk live training telemetry: ms/tree, rows/s,
    last-iteration and (when the objective can compute it cheaply)
    train-loss gauges on ``train_stats``, plus one ``boost_chunk``
    journal event — the numbers ``tools/chaos_training.py`` and the
    serving bench read from telemetry instead of ad-hoc prints.

    ``scores`` may be a device array; it is only fetched when the
    objective implements ``train_loss`` and the array is fully
    addressable (a multi-controller mesh shard is not — loss is skipped
    there rather than gathering the gang's scores).  The fetch is
    bounded: beyond ``_MONITOR_LOSS_MAX_ROWS`` rows the loss is
    computed on a strided sample, sliced ON DEVICE first, so a
    Criteo-scale fit pays a bounded D2H per boundary for the gauge, not
    an O(n) transfer the training loop never needed before.

    ``coll_sched``: the fit's per-tree collective accounting
    (grower.collective_schedule) — scaled by the chunk's tree count into
    the ``collective_count``/``collective_payload_bytes`` counters and
    journaled on the ``boost_chunk`` event, so the payload a wide-data
    voting fit saves is machine-checkable on /metrics (ISSUE 16)."""
    iters = max(1, it1 - it0)
    trees = iters * max(1, K)
    ms_per_tree = dt_s * 1e3 / trees
    rows_per_s = n_rows * iters / dt_s if dt_s > 0 else 0.0
    train_stats.set_gauge("ms_per_tree", round(ms_per_tree, 3))
    train_stats.set_gauge("train_rows_per_s", round(rows_per_s, 1))
    train_stats.set_gauge("last_iteration", float(it1))
    train_stats.incr("boost_chunks")
    coll_count = coll_bytes = None
    if coll_sched is not None:
        coll_count = coll_sched["count"] * trees
        coll_bytes = coll_sched["payload_bytes"] * trees
        train_stats.incr("collective_count", coll_count)
        train_stats.incr("collective_payload_bytes", coll_bytes)
    loss = None
    if (objective is not None and scores is not None
            and labels is not None
            and getattr(scores, "is_fully_addressable", True)):
        try:
            labels_np = np.asarray(labels)
            stride = max(1, len(labels_np) // _MONITOR_LOSS_MAX_ROWS)
            if stride > 1:
                scores = scores[::stride]    # device-side slice: the
                labels_np = labels_np[::stride]   # D2H stays bounded
                weights = (None if weights is None
                           else np.asarray(weights)[::stride])
            loss = objective.train_loss(np.asarray(scores), labels_np,
                                        weights)
        except Exception:  # noqa: BLE001 - telemetry must never kill
            loss = None    # the fit it observes
    if loss is not None:
        train_stats.set_gauge("train_loss", round(float(loss), 6))
    ev = {"fit": _tm.current_fit_span(), "it_start": int(it0),
          "it_end": int(it1), "ms_per_tree": round(ms_per_tree, 3),
          "rows_per_s": round(rows_per_s, 1),
          "hist_method": hist_method, "collective": collective}
    if coll_count is not None:
        ev["collective_count"] = int(coll_count)
        ev["collective_payload_bytes"] = int(coll_bytes)
    if loss is not None:
        ev["train_loss"] = round(float(loss), 6)
    _tm.get_journal().emit("boost_chunk", **ev)


def _ckpt_glob(template: str) -> str:
    """Glob pattern for a checkpoint filename template, derived from the
    template's own format fields (every ``{...}`` becomes ``*``) so a
    template change can never silently orphan files."""
    import re
    return re.sub(r"\{[^{}]*\}", "*", template)


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed file survives power loss (the
    rename itself lives in the directory's metadata; fsyncing the file
    alone is not enough).  Best-effort: some platforms refuse directory
    fds, and a checkpoint must never kill the fit it protects."""
    import os
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _ckpt_fingerprint(n, f, K, params, labels, bins, weights,
                      init_scores) -> str:
    """Identity of a fit for resume safety: shapes, every param that
    shapes the boosting trajectory (checkpoint_dir itself excluded so
    moving the directory doesn't orphan the snapshot), AND a digest of
    the data — full labels, weights, init scores (the continued-training
    margins: a re-run with a different initModelPath must NOT resume the
    old trajectory) plus a strided sample of the binned matrix — so a
    same-shape fit on DIFFERENT inputs starts fresh instead of silently
    blending two fits."""
    import hashlib
    # checkpoint_dir/checkpoint_chunk shape WHERE and HOW OFTEN snapshots
    # land, never the boosting trajectory — excluded so moving the
    # directory or retuning the boundary cadence doesn't orphan a resume
    d = {k: v for k, v in params.__dict__.items()
         if k not in ("checkpoint_dir", "checkpoint_chunk")}
    h = hashlib.sha256(
        f"{n}|{f}|{K}|{sorted(d.items())!r}".encode("utf-8"))
    h.update(np.ascontiguousarray(np.asarray(labels)).tobytes())
    h.update(b"w" if weights is None else
             np.ascontiguousarray(np.asarray(weights)).tobytes())
    h.update(b"i" if init_scores is None else
             np.ascontiguousarray(np.asarray(init_scores)).tobytes())
    bins_np = np.asarray(bins)
    h.update(np.ascontiguousarray(
        bins_np[:: max(1, len(bins_np) // 4096)]).tobytes())
    return h.hexdigest()


def _ckpt_save(ckpt_dir, fp, it, trees_chunks, scores, val_scores,
               cur_bag, rng, bag_rng, best_metric, best_iter) -> None:
    """Persist the chunk-boundary state.

    Tree chunks are immutable once grown, so each is written to its own
    file exactly ONCE (O(1) device→host transfer and disk I/O per
    boundary, not O(chunks)); the small meta/state file — host copies of
    the device score vectors (float32 round-trips exactly), the two host
    RNG streams (bit-generator state as JSON), the carried bag mask and
    the early-stopping bests — is replaced atomically (tmp + fsync +
    rename) last, so a torn save leaves the PREVIOUS boundary loadable.
    A resumed fit replays the remaining chunks on bit-identical inputs."""
    import os
    os.makedirs(ckpt_dir, exist_ok=True)
    _ckpt_write_chunks(ckpt_dir, trees_chunks)
    _ckpt_write_meta(
        ckpt_dir, fp, it, len(trees_chunks), rng, bag_rng, best_metric,
        best_iter,
        arrays={"scores": np.asarray(scores),
                "val_scores": np.asarray(val_scores),
                "cur_bag": np.asarray(cur_bag)},
        extra_meta={"n_trees": _ckpt_tree_count(trees_chunks),
                    "fit_span": _tm.current_fit_span()})
    train_stats.incr("ckpt_saved")
    _ckpt_event("ckpt_saved", it=int(it), n_chunks=len(trees_chunks))


def _ckpt_tree_count(trees_chunks) -> int:
    """Total trees across the chunk list — endorsed by the meta so a
    load can detect a STALE over-meta chunk file.  The write-once skip
    in :func:`_ckpt_write_chunks` is only sound while the chunk CADENCE
    is unchanged: ``checkpoint_chunk`` is deliberately outside the
    fingerprint (retuning it must not orphan a resume), so a crash
    between a chunk write and its meta replace, followed by a resume
    with a different cadence, can leave file ``n`` holding a different
    iteration count than the new meta implies — identical VALUES are
    guaranteed by bit-identical replay, counts are not.  Validating
    the endorsed total at load turns that silent wrong-forest into a
    discard-and-start-fresh."""
    # shape alone: no D2H transfer for device-resident mesh chunks
    return int(sum(ch[0].shape[0] for ch in trees_chunks))


def _ckpt_read_chunks(ckpt_dir, n_chunks, n_trees=None):
    """Load the write-once tree chunk files, closing each npz (a
    lingering NpzFile holds its zip member open; resumed gangs would
    otherwise accumulate one fd per chunk per process).  When the
    meta's endorsed ``n_trees`` is given, a total-count mismatch —
    a stale over-meta chunk from a different ``checkpoint_chunk``
    cadence (see :func:`_ckpt_tree_count`) — raises, which the load
    paths turn into discard-and-start-fresh."""
    import os
    chunks = []
    for i in range(n_chunks):
        with np.load(os.path.join(ckpt_dir, _CKPT_CHUNK.format(i))) as cz:
            chunks.append(TreeArrays(*[cz[name]
                                       for name in TreeArrays._fields]))
    if n_trees is not None and _ckpt_tree_count(chunks) != n_trees:
        raise ValueError(
            f"tree chunk files hold {_ckpt_tree_count(chunks)} trees "
            f"but the checkpoint meta endorses {n_trees} (stale chunk "
            f"from a different checkpoint_chunk cadence)")
    return chunks


def _ckpt_write_chunks(ckpt_dir, trees_chunks) -> None:
    """Write-once tree chunk files (fsync'd, atomic rename each)."""
    import os
    for i, ch in enumerate(trees_chunks):
        cpath = os.path.join(ckpt_dir, _CKPT_CHUNK.format(i))
        if os.path.exists(cpath):
            continue
        tmp = cpath + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, **{name: np.asarray(arr) for name, arr
                            in zip(TreeArrays._fields, ch)})
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, cpath)


def _ckpt_write_meta(ckpt_dir, fp, it, n_chunks, rng, bag_rng,
                     best_metric, best_iter, arrays, extra_meta=None
                     ) -> None:
    """The small meta/state file, replaced atomically LAST so a torn
    save leaves the previous boundary loadable; the containing
    directory is fsync'd after the rename so the rename itself survives
    power loss (the file fsync alone only makes the INODE durable, not
    the directory entry pointing at it)."""
    import json as _json
    import os
    meta = {
        "fingerprint": fp, "it": int(it),
        "n_chunks": int(n_chunks),
        "rng_state": rng.bit_generator.state,
        "bag_rng_state": bag_rng.bit_generator.state,
        "best_metric": float(best_metric), "best_iter": int(best_iter),
    }
    if extra_meta:
        meta.update(extra_meta)
    tmp = os.path.join(ckpt_dir, _CKPT_FILE + ".tmp")
    with open(tmp, "wb") as fh:
        np.savez(fh,
                 __meta__=np.frombuffer(
                     _json.dumps(meta).encode("utf-8"), np.uint8),
                 **arrays)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(ckpt_dir, _CKPT_FILE))
    _fsync_dir(ckpt_dir)


def _ckpt_load(ckpt_dir, fp):
    """Load and validate a snapshot; None when absent/torn/mismatched —
    a bad snapshot must degrade to a fresh fit, never kill the re-run."""
    import json as _json
    import os
    path = os.path.join(ckpt_dir, _CKPT_FILE)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            meta = _json.loads(bytes(z["__meta__"]).decode("utf-8"))
            if meta["fingerprint"] != fp:
                log.warning("checkpoint at %s belongs to a different "
                            "fit (data or params changed); starting "
                            "fresh", path)
                train_stats.incr("ckpt_discarded")
                _ckpt_event("ckpt_discarded",
                            reason="fingerprint_mismatch")
                return None
            arrays = {k: z[k] for k in ("scores", "val_scores",
                                        "cur_bag")}
        return {
            "it": meta["it"],
            "trees_chunks": _ckpt_read_chunks(ckpt_dir,
                                              meta["n_chunks"],
                                              meta.get("n_trees")),
            "scores": arrays["scores"],
            "val_scores": arrays["val_scores"],
            "cur_bag": arrays["cur_bag"],
            "rng_state": meta["rng_state"],
            "bag_rng_state": meta["bag_rng_state"],
            "best_metric": meta["best_metric"],
            "best_iter": meta["best_iter"],
        }
    except Exception as e:  # noqa: BLE001 - torn/partial snapshot
        # degrade-to-fresh-fit is the right behavior, but the REASON
        # must be diagnosable — silent checkpoint loss looks identical
        # to "no checkpoint existed" in the logs otherwise
        log.warning("checkpoint at %s is unreadable (%s: %s); "
                    "starting fresh", path, type(e).__name__, e)
        train_stats.incr("ckpt_discarded")
        _ckpt_event("ckpt_discarded", reason=type(e).__name__)
        return None


def _ckpt_clear(ckpt_dir) -> None:
    import glob
    import os
    # ".tmp" partials too: a crash mid-atomic-write leaves one behind,
    # and the resumed fit may never rewrite that index
    paths = [os.path.join(ckpt_dir, _CKPT_FILE),
             os.path.join(ckpt_dir, _CKPT_FILE + ".tmp")]
    for tpl in (_CKPT_CHUNK, _CKPT_MESH_STATE):
        for pat in (_ckpt_glob(tpl), _ckpt_glob(tpl) + ".tmp"):
            paths += glob.glob(os.path.join(ckpt_dir, pat))
    for p in paths:
        try:
            os.remove(p)
        except OSError:
            pass


def _ckpt_fingerprint_mesh(n, f, K, params, labels, bins, w,
                           init_scores, mesh, shard_data=None) -> str:
    """Mesh-fit resume fingerprint: the serial digest plus the mesh
    topology (shape and process count), so a resume under a different
    shard layout starts fresh instead of scattering shards wrongly.

    Under sharded ingestion the digest covers only GLOBAL metadata
    (params, concatenated labels/weights/init-scores, per-shard sizes)
    — inputs every controller shares — so the shared fingerprint is
    identical on every process with no coordination round.  Feature
    VALUES are covered per process by :func:`_local_bins_digest`
    (stored in each state file, validated locally, and made unanimous
    by the gang gate in ``_train_distributed``)."""
    import hashlib
    from ..core.mesh import DATA_AXIS
    if shard_data is not None:
        sizes = list(shard_data["sizes"])
        y_cat = np.concatenate(
            [np.asarray(y) for y in shard_data["label_shards"]])
        w_cat = np.concatenate(
            [np.asarray(ws) for ws in shard_data["weight_shards"]])
        iss = shard_data.get("init_score_shards")
        is_cat = (None if iss is None or any(s is None for s in iss)
                  else np.concatenate([np.asarray(s) for s in iss]))
        base = _ckpt_fingerprint(n, f, K, params, y_cat,
                                 np.zeros((0, f), np.uint8), w_cat,
                                 is_cat)
        base = hashlib.sha256(
            (base + "|sizes=" + ",".join(map(str, sizes))
             ).encode("utf-8")).hexdigest()
    else:
        base = _ckpt_fingerprint(n, f, K, params, labels, bins, w,
                                 init_scores)
    from .distributed import _feat_n
    topo = (f"|mesh={int(mesh.shape[DATA_AXIS])}x"
            f"{_feat_n(mesh)}"
            f"|procs={jax.process_count()}")
    return hashlib.sha256((base + topo).encode("utf-8")).hexdigest()


def _local_bins_digest(shard_data) -> str:
    """Digest of the per-process inputs THIS process contributes under
    sharded ingestion: its feature shards AND its init-score shards.
    The shared mesh fingerprint can only cover metadata every
    controller holds (labels, weights, sizes) — init scores are
    excluded there too, because under multicontroller ingestion every
    process holds ``None`` in its peers' slots.  Without this digest a
    re-run on re-extracted feature values, or a continuation re-run
    with a different ``initModelPath``'s margins, would silently
    resume and blend two fits — the exact failure
    ``_ckpt_fingerprint`` hashes ``bins`` and ``init_scores`` to
    prevent on the serial path.  Non-sharded mesh fits return ""
    (their bins and init scores are already in the shared
    fingerprint)."""
    import hashlib
    if shard_data is None:
        return ""
    h = hashlib.sha256()
    for b in shard_data["bins_shards"]:
        if b is not None:
            h.update(np.ascontiguousarray(np.asarray(b)).tobytes())
    iss = shard_data.get("init_score_shards")
    if iss is not None:
        for i, s in enumerate(iss):
            if s is not None:
                # slot index tagged so present/absent layout changes
                # can never alias
                h.update(f"|is{i}|".encode("utf-8"))
                h.update(np.ascontiguousarray(
                    np.asarray(s, np.float32)).tobytes())
    return h.hexdigest()


def _ckpt_shard_bounds(index, shape):
    """Normalize an addressable-shard index (tuple of slices) to
    JSON-able ``[[start, stop], ...]`` bounds."""
    return [list(s.indices(dim)[:2]) for s, dim in zip(index, shape)]


def _ckpt_save_mesh(ckpt_dir, fp, it, trees_chunks, scores, val_scores,
                    cur_bag, rng, bag_rng, best_metric, best_iter,
                    local_digest="") -> None:
    """Mesh/multicontroller chunk-boundary snapshot.

    Write order gives crash consistency without any cross-process
    commit protocol:

    1. every process writes its OWN it-stamped state file — the
       addressable shards of the (sharded, possibly non-fully-
       addressable) score vectors plus the host-side bag mask —
       atomically (tmp + fsync + rename);
    2. processes barrier (``sync_global_devices``) so the meta can
       never name a boundary some peer hasn't persisted;
    3. process 0 replaces the meta file (fingerprint, it, RNG streams,
       early-stopping bests) and fsyncs the directory;
    4. each process garbage-collects its own OLDER state generations.

    A crash anywhere leaves the meta pointing at a complete, durable
    state generation: before step 3 the previous generation's files are
    still on disk (step 4 hasn't run), after step 3 the new generation
    is fully written.  Tree chunks are write-once and shared (trees are
    replicated across the mesh), so process 0 alone persists them.
    """
    import glob
    import os
    pid = jax.process_index()
    nproc = jax.process_count()
    os.makedirs(ckpt_dir, exist_ok=True)
    if pid == 0:
        _ckpt_write_chunks(ckpt_dir, trees_chunks)
    arrays = {"cur_bag": np.asarray(cur_bag)}
    shards_meta = []
    seen = set()
    for name, arr in (("scores", scores), ("val_scores", val_scores)):
        for sh in arr.addressable_shards:
            bounds = _ckpt_shard_bounds(sh.index, arr.shape)
            key = (name, str(bounds))
            if key in seen:      # replicas (e.g. along the feature axis)
                continue
            seen.add(key)
            arrays[f"shard_{len(shards_meta)}"] = np.asarray(sh.data)
            shards_meta.append({"name": name, "bounds": bounds})
    import json as _json
    pmeta = {"fingerprint": fp, "it": int(it), "pid": pid,
             "local_digest": local_digest, "shards": shards_meta}
    spath = os.path.join(ckpt_dir, _CKPT_MESH_STATE.format(pid, int(it)))
    tmp = spath + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh,
                 __meta__=np.frombuffer(
                     _json.dumps(pmeta).encode("utf-8"), np.uint8),
                 **arrays)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, spath)
    _fsync_dir(ckpt_dir)
    if nproc > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt_save_{it}")
    if pid == 0:
        _ckpt_write_meta(ckpt_dir, fp, it, len(trees_chunks), rng,
                         bag_rng, best_metric, best_iter, arrays={},
                         extra_meta={"nproc": nproc, "mesh": True,
                                     "n_trees": _ckpt_tree_count(
                                         trees_chunks),
                                     "fit_span":
                                         _tm.current_fit_span()})
    if nproc > 1:
        # second barrier: no peer may GC its PREVIOUS generation until
        # the meta naming the new one is durable — otherwise a gang
        # crash in the window between a peer's GC and process 0's meta
        # replace leaves the meta pointing at a generation whose state
        # files are already gone (full restart instead of bounded loss)
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt_meta_{it}")
    # GC this process's older state generations (the meta naming `it`
    # is durable for every process once written, and peers never read
    # another process's shard data, only its __meta__ for validation)
    own_glob = _CKPT_MESH_PREFIX.format(pid) + "*"
    for p in glob.glob(os.path.join(ckpt_dir, own_glob)):
        if p != spath:
            try:
                os.remove(p)
            except OSError:
                pass
    train_stats.incr("ckpt_saved")
    _ckpt_event("ckpt_saved", it=int(it), n_chunks=len(trees_chunks),
                pid=pid, mesh=True)


def _ckpt_load_mesh(ckpt_dir, fp, scores_like, val_scores_like,
                    local_digest=""):
    """Validate and load a mesh snapshot; None when absent/torn/
    mismatched (degrade to a fresh fit, exactly like the serial path).

    The shared parts of the verdict — meta present, fingerprint match,
    one state file per process stamped with the meta's ``it`` and
    fingerprint — are a pure function of the SHARED checkpoint
    directory, so every controller reaches them identically with no
    coordination round.  The ``local_digest`` check (this process's own
    feature data) can legitimately diverge across processes; the caller
    makes the final verdict unanimous with a gang allgather.  Each
    process materializes only its own state file's arrays; peers' files
    are opened for their ``__meta__`` validation alone.
    """
    import json as _json
    import os
    path = os.path.join(ckpt_dir, _CKPT_FILE)
    if not os.path.exists(path):
        return None
    pid = jax.process_index()
    try:
        with np.load(path) as z:
            meta = _json.loads(bytes(z["__meta__"]).decode("utf-8"))
        if meta["fingerprint"] != fp:
            log.warning("mesh checkpoint at %s belongs to a different "
                        "fit (data, params or topology changed); "
                        "starting fresh", path)
            train_stats.incr("ckpt_discarded")
            _ckpt_event("ckpt_discarded",
                        reason="fingerprint_mismatch", mesh=True)
            return None
        it = meta["it"]
        nproc = meta.get("nproc", 1)
        own_meta, own_arrays = None, None
        for p in range(nproc):
            spath = os.path.join(ckpt_dir,
                                 _CKPT_MESH_STATE.format(p, it))
            # materialize-and-close: peers' files are opened for their
            # __meta__ alone, and a lingering NpzFile leaks one fd per
            # peer per resume
            with np.load(spath) as sz:
                pmeta = _json.loads(
                    bytes(sz["__meta__"]).decode("utf-8"))
                if pmeta["fingerprint"] != fp or pmeta["it"] != it:
                    raise ValueError(
                        f"state file for process {p} does not match "
                        f"the checkpoint meta (boundary {it})")
                if p == pid:
                    own_meta = pmeta
                    own_arrays = {k: sz[k] for k in sz.files
                                  if k != "__meta__"}
        if own_meta.get("local_digest", "") != local_digest:
            # cheap string check FIRST: rejecting here must not pay the
            # full-forest chunk read below
            log.warning("mesh checkpoint state for process %d was "
                        "written against different local feature data; "
                        "starting fresh", pid)
            train_stats.incr("ckpt_discarded")
            _ckpt_event("ckpt_discarded", reason="local_digest",
                        mesh=True)
            return None
        chunks = _ckpt_read_chunks(ckpt_dir, meta["n_chunks"],
                                   meta.get("n_trees"))
        lookup = {}
        for i, sm in enumerate(own_meta["shards"]):
            lookup[(sm["name"], str(sm["bounds"]))] = \
                own_arrays[f"shard_{i}"]

        def restore(name, like):
            def cb(index):
                bounds = _ckpt_shard_bounds(index, like.shape)
                return lookup[(name, str(bounds))]
            return jax.make_array_from_callback(
                like.shape, like.sharding, cb)

        return {
            "it": it, "trees_chunks": chunks,
            "scores": restore("scores", scores_like),
            "val_scores": restore("val_scores", val_scores_like),
            "cur_bag": np.asarray(own_arrays["cur_bag"]),
            "rng_state": meta["rng_state"],
            "bag_rng_state": meta["bag_rng_state"],
            "best_metric": meta["best_metric"],
            "best_iter": meta["best_iter"],
        }
    except Exception as e:  # noqa: BLE001 - torn/partial snapshot
        log.warning("mesh checkpoint at %s is unusable (%s: %s); "
                    "starting fresh", path, type(e).__name__, e)
        train_stats.incr("ckpt_discarded")
        _ckpt_event("ckpt_discarded", reason=type(e).__name__,
                    mesh=True)
        return None


@functools.partial(jax.jit,
                   static_argnames=("obj", "cfg", "lr", "has_val", "rf"),
                   donate_argnums=(1, 7))
def _boost_scan(bins, scores, labels, weights, bag_masks, fi_stack,
                val_bins, val_scores, obj: Objective, cfg: GrowerConfig,
                lr: float, has_val: bool, rf: bool = False, efb=None):
    """A chunk of boosting iterations inside ONE compiled program.

    ``bag_masks``: (C, n) bagging masks, or (C, 1) broadcast when bagging
    is off; ``fi_stack``: (C, f, 3) per-iteration feature info.  Returns
    (stacked shrunk trees, scores, val_scores, per-iter val scores).

    One launch per chunk instead of per iteration: on a tunneled TPU every
    dispatch pays a ~ms RPC floor (BENCH_SWEEP.md), so the loop-of-steps
    formulation spent more wall-clock in launch gaps than on device.  The
    scan also lets XLA pipeline tree t's tail with tree t+1's head.  This
    is the TPU-shaped analog of the reference keeping the whole iteration
    loop behind one JNI call (SURVEY.md §3.1).
    """
    binsT = bins.T   # fit-invariant; hoisted out of the scan (PERF.md r4)

    def body(carry, xs):
        scores, val_scores = carry
        bag, fi = xs
        bag = jnp.broadcast_to(bag, scores.shape)
        g, h = obj.grad_hess(scores, labels, weights)
        gh = jnp.stack([g * bag, h * bag, bag], axis=1)
        tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg, efb,
                                         binsT=binsT)
        if not rf:
            # rf (random forest): every tree fits the gradient at the
            # CONSTANT init scores, unshrunk; averaging happens at export
            scores = scores + lr * tree.leaf_value[row_leaf]
            tree = apply_shrinkage(tree, lr)
        if has_val:
            val_scores = val_scores + predict_tree_binned(
                tree, val_bins, cfg.num_leaves)
            out_val = val_scores
        else:
            out_val = _dummy_val(1)
        return (scores, val_scores), (tree, out_val)

    (scores, val_scores), (trees, val_hist) = jax.lax.scan(
        body, (scores, val_scores), (bag_masks, fi_stack))
    return trees, scores, val_scores, val_hist


def _dart_draw_drops(dart_rng, n_trees: int, params) -> np.ndarray:
    """Per-iteration dart dropout draw — ONE shared RNG-stream consumer so
    the serial and mesh dart loops make bit-identical dropout decisions
    for the same dropSeed (the serial↔mesh parity contract)."""
    if n_trees and dart_rng.random() >= params.skip_drop:
        sel = np.nonzero(dart_rng.random(n_trees) < params.drop_rate)[0]
        # maxDrop <= 0 means "no limit" (LightGBM max_drop docs)
        if params.max_drop > 0 and len(sel) > params.max_drop:
            sel = dart_rng.choice(sel, size=params.max_drop,
                                  replace=False)
        return sel
    return np.zeros(0, np.int64)


def _dart_host_loop(T, K, dart_rng, params, scores, bag_draw, fi_draw,
                    grow_unit, unit_margin, callbacks, val_hook=None,
                    units_out=None):
    """THE dart dropout bookkeeping — serial and mesh run this one loop
    (the serial↔mesh same-dropSeed parity contract holds by
    construction).  Per iteration: draw drops, subtract the dropped
    units' scaled margins, grow at the dropped-out scores via
    ``grow_unit(s_minus, bag, fi) -> (unit, b_new)``, apply the 1/(k+1)
    normalization, rescale the dropped units.  ``unit_margin(unit)``
    scores a unit on the TRAINING rows; ``val_hook(it, unit, sel,
    scales, norm)`` (optional) sees the PRE-update scales, matching the
    validation-margin algebra.  Returns (units, flat trees_list
    iteration-major class-minor, per-iteration scales, scores)."""
    units: List[TreeArrays] = units_out if units_out is not None else []
    trees_list: List[TreeArrays] = []
    scales: List[float] = []
    for it in range(T):
        bag = bag_draw(it)
        fi = fi_draw(it)
        sel = _dart_draw_drops(dart_rng, len(units), params)
        k = len(sel)
        if k:
            P = scales[sel[0]] * unit_margin(units[sel[0]])
            for i in sel[1:]:
                P = P + scales[i] * unit_margin(units[i])
            s_minus = scores - P
        else:
            s_minus = scores
        unit, b_new = grow_unit(s_minus, bag, fi)
        norm = 1.0 / (k + 1)
        scores = s_minus + norm * b_new
        if k:
            scores = scores + (k * norm) * P
        if val_hook is not None:
            val_hook(it, unit, sel, scales, norm)
        if k:
            for i in sel:
                scales[i] *= k * norm
        units.append(unit)
        scales.append(norm)
        if K == 1:
            trees_list.append(unit)
        else:
            trees_list.extend(
                jax.tree_util.tree_map(lambda a, kk=kk: a[kk], unit)
                for kk in range(K))
        if callbacks:
            for cb in callbacks:
                cb(it, trees_list)
    return units, trees_list, scales, scores


@functools.partial(jax.jit, static_argnames=("obj", "cfg", "lr", "K"))
def _dart_step(bins, binsT, s_minus, labels, weights, bag, fi,
               obj: Objective, cfg: GrowerConfig, lr: float, K: int = 1,
               efb=None):
    """One dart iteration body: fit tree(s) to the gradient at the
    dropped-out score vector; returns the lr-shrunk tree(s) and the base
    contribution (the host applies the 1/(k+1) dart normalization).
    ``binsT`` is the fit-invariant transpose, computed once by the caller.

    ``K > 1`` (multiclass): LightGBM's dart drops whole ITERATIONS — the
    K class trees of an iteration share one weight — so the step grows K
    trees at the shared dropped-out scores and returns them stacked
    (K, ...) with a (n, K) contribution."""
    g, h = obj.grad_hess(s_minus, labels, weights)
    if K == 1:
        gh = jnp.stack([g * bag, h * bag, bag], axis=1)
        tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg, efb,
                                         binsT=binsT)
        tree = apply_shrinkage(tree, lr)
        return tree, tree.leaf_value[row_leaf]
    trees_k, bnews = [], []
    for k in range(K):
        gh = jnp.stack([g[:, k] * bag, h[:, k] * bag, bag], axis=1)
        tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg, efb,
                                         binsT=binsT)
        tree = apply_shrinkage(tree, lr)
        trees_k.append(tree)
        bnews.append(tree.leaf_value[row_leaf])
    trees = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees_k)
    return trees, jnp.stack(bnews, axis=1)


@functools.partial(jax.jit, static_argnames=("L", "num_bins"))
def _dart_iter_margin(trees_st, bins, L: int, efb=None,
                      num_bins: int = 256):
    """(n, K) margins of one dart iteration's K stacked trees (``efb``:
    bins hold bundle columns; the walk decodes per level)."""
    if efb is None:
        return jax.vmap(
            lambda t: predict_tree_binned(t, bins, L))(trees_st).T
    return jax.vmap(
        lambda t: predict_tree_binned_efb(t, bins, L, efb, num_bins)
    )(trees_st).T


@functools.partial(jax.jit,
                   static_argnames=("obj", "cfg", "lr", "k1", "k2", "amp",
                                    "has_val", "K"),
                   donate_argnums=(1, 7))
def _boost_scan_goss(bins, scores, labels, weights, keys, fi_stack,
                     val_bins, val_scores, obj: Objective, cfg: GrowerConfig,
                     lr: float, k1: int, k2: int, amp: float, has_val: bool,
                     K: int = 1, efb=None):
    """GOSS chunk: each iteration grows its tree on the top-|g·h| rows plus
    an amplified random sample of the rest (Ke et al. 2017; LightGBM
    boosting=goss).  Histogram work shrinks to ``(topRate + otherRate)·n``
    rows via a gather; scores still update for every row via a full binned
    traversal of the new tree.

    ``K > 1`` (multiclass): rows rank by the class-summed influence
    Σ_k |g_k·h_k| and ONE sample feeds all K per-class trees, matching
    LightGBM's multiclass GOSS (one sampling pass per iteration)."""
    # pre-gather checks: GOSS hands _grow_tree_impl only the influence
    # SAMPLE, but predict_tree_binned walks the FULL matrix every
    # iteration, and the argsort pushes NaN rows to the sample's tail —
    # so both invariants must look at the unsampled inputs here
    _debug.check_bins_in_range(bins, cfg.num_bins)

    def train_pred(tree):
        # scores update walks the TRAINING matrix; under EFB it holds
        # bundle columns, so the walk decodes per level (validation
        # matrices are never bundled and keep the plain walk)
        return predict_tree_binned_any(tree, bins, cfg.num_leaves,
                                       efb, cfg.num_bins)

    def body(carry, xs):
        scores, val_scores = carry
        key, fi = xs
        g, h = obj.grad_hess(scores, labels, weights)
        _debug.check_finite("gradients/hessians", g, h)
        n = g.shape[0]
        infl = (jnp.abs(g * h) if K == 1
                else jnp.sum(jnp.abs(g * h), axis=1))
        rank = jnp.argsort(-infl)                    # descending influence
        top_idx = rank[:k1]
        rest = rank[k1:]
        rk = jax.random.uniform(key, (n - k1,))
        other_idx = jnp.take(rest, jnp.argsort(rk)[:k2])
        idx = jnp.concatenate([top_idx, other_idx])
        amp_vec = jnp.concatenate([
            jnp.ones(k1, jnp.float32), jnp.full(k2, amp, jnp.float32)])
        bins_g = jnp.take(bins, idx, axis=0)
        if K == 1:
            gh = jnp.stack([jnp.take(g, idx) * amp_vec,
                            jnp.take(h, idx) * amp_vec,
                            jnp.ones(k1 + k2, jnp.float32)], axis=1)
            tree, _ = _grow_tree_impl(bins_g, gh, fi, cfg, efb)
            scores = scores + lr * train_pred(tree)
            trees = apply_shrinkage(tree, lr)
            if has_val:
                val_scores = val_scores + predict_tree_binned(
                    trees, val_bins, cfg.num_leaves)
        else:
            trees_k = []
            for k in range(K):
                gh = jnp.stack([jnp.take(g[:, k], idx) * amp_vec,
                                jnp.take(h[:, k], idx) * amp_vec,
                                jnp.ones(k1 + k2, jnp.float32)], axis=1)
                tree, _ = _grow_tree_impl(bins_g, gh, fi, cfg, efb)
                scores = scores.at[:, k].add(lr * train_pred(tree))
                tree = apply_shrinkage(tree, lr)
                if has_val:
                    val_scores = val_scores.at[:, k].add(
                        predict_tree_binned(tree, val_bins,
                                            cfg.num_leaves))
                trees_k.append(tree)
            trees = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *trees_k)
        out_val = val_scores if has_val else _dummy_val(K)
        return (scores, val_scores), (trees, out_val)

    (scores, val_scores), (trees, val_hist) = jax.lax.scan(
        body, (scores, val_scores), (keys, fi_stack))
    if K > 1:
        trees = jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), trees)
    return trees, scores, val_scores, val_hist


@functools.partial(jax.jit,
                   static_argnames=("obj", "cfg", "lr", "K", "has_val",
                                    "rf"),
                   donate_argnums=(1, 7))
def _boost_scan_multi(bins, scores, labels, weights, bag_masks, fi_stack,
                      val_bins, val_scores, obj: Objective,
                      cfg: GrowerConfig, lr: float, K: int, has_val: bool,
                      efb=None, rf: bool = False):
    """Multiclass chunk: grad/hess computed ONCE per iteration for all K
    trees (LightGBM softmax semantics), then K grow steps consume the fixed
    gradients.  Emits trees flattened to (C*K, ...), iteration-major,
    class-minor — the order the model file expects.

    ``rf``: random-forest mode — every tree fits the gradient at the
    CONSTANT init scores, unshrunk (per-class averaging at export)."""
    binsT = bins.T   # fit-invariant; hoisted out of the scan (PERF.md r4)

    def body(carry, xs):
        scores, val_scores = carry
        bag, fi = xs
        bag = jnp.broadcast_to(bag, (scores.shape[0],))
        g, h = obj.grad_hess(scores, labels, weights)
        trees_k = []
        for k in range(K):
            gh = jnp.stack([g[:, k] * bag, h[:, k] * bag, bag], axis=1)
            tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg, efb,
                                             binsT=binsT)
            if not rf:
                scores = scores.at[:, k].add(
                    lr * tree.leaf_value[row_leaf])
                tree = apply_shrinkage(tree, lr)
            if has_val:
                val_scores = val_scores.at[:, k].add(predict_tree_binned(
                    tree, val_bins, cfg.num_leaves))
            trees_k.append(tree)
        trees = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees_k)
        out_val = val_scores if has_val else _dummy_val(K)
        return (scores, val_scores), (trees, out_val)

    (scores, val_scores), (trees, val_hist) = jax.lax.scan(
        body, (scores, val_scores), (bag_masks, fi_stack))
    trees = jax.tree_util.tree_map(
        lambda a: a.reshape((-1,) + a.shape[2:]), trees)
    return trees, scores, val_scores, val_hist


@jax.jit
def _pack_trees_stacked(stacked: TreeArrays) -> jnp.ndarray:
    """Flatten stacked (T, ...) TreeArrays into one (T, P) f32 buffer.

    Device→host latency dominates on a tunneled TPU (each transfer costs
    ~the round-trip time regardless of size), so the whole forest crosses
    in ONE transfer instead of 12 per tree.  int fields fit f32 exactly
    (node/feature/bin ids ≪ 2^24); counts are already f32 on device.
    Packing happens *inside* jit so trees produced under shard_map (multi-
    device, replicated) are legal inputs — XLA inserts the resharding.
    """
    f32 = lambda a: a.astype(jnp.float32)  # noqa: E731
    T = stacked.node_cat_bits.shape[0]
    bits = stacked.node_cat_bits.reshape(T, -1)
    # u32 words don't fit f32 exactly; ship two u16 halves (both exact)
    bits_lo = f32(bits & jnp.uint32(0xFFFF))
    bits_hi = f32(bits >> jnp.uint32(16))
    return jnp.concatenate([
        f32(stacked.num_leaves)[:, None],
        f32(stacked.node_feat), f32(stacked.node_bin),
        f32(stacked.node_left), f32(stacked.node_right),
        stacked.node_gain, stacked.node_value,
        stacked.node_weight, stacked.node_count,
        f32(stacked.node_is_cat),
        stacked.leaf_value, stacked.leaf_weight, stacked.leaf_count,
        bits_lo, bits_hi,
    ], axis=1)


def _fetch_host_trees(chunks: List[TreeArrays], num_leaves: int,
                      mapper: BinMapper) -> Tuple[List[HostTree], np.ndarray]:
    """Batched device→host transfers → per-tree HostTrees + leaf counts.

    ``chunks``: stacked (C_i, ...) TreeArrays pytrees as produced by the
    scan steps — one packed transfer per chunk (typically one per fit)."""
    if not chunks:
        return [], np.zeros(0, np.int64)
    packed = np.concatenate(
        [np.asarray(_pack_trees_stacked(c)) for c in chunks])
    L, m = num_leaves, num_leaves - 1
    W = chunks[0].node_cat_bits.shape[-1]
    offs = np.cumsum([1] + [m] * 9 + [L] * 3 + [m * W] * 2)
    cols = [packed[:, a:b] for a, b in zip([0] + list(offs), offs)]
    nls = cols[0][:, 0].astype(np.int64)
    out = []
    for i in range(packed.shape[0]):
        bits = (cols[13][i].astype(np.uint32)
                | (cols[14][i].astype(np.uint32) << np.uint32(16)))
        tree = TreeArrays(
            node_feat=cols[1][i].astype(np.int32),
            node_bin=cols[2][i].astype(np.int32),
            node_left=cols[3][i].astype(np.int32),
            node_right=cols[4][i].astype(np.int32),
            node_gain=cols[5][i], node_value=cols[6][i],
            node_weight=cols[7][i], node_count=cols[8][i],
            node_is_cat=cols[9][i].astype(np.int32),
            node_cat_bits=bits.reshape(m, W),
            leaf_value=cols[10][i], leaf_weight=cols[11][i],
            leaf_count=cols[12][i], num_leaves=nls[i])
        out.append(host_tree_from_arrays(tree, mapper, mapper.missing_bin))
    return out, nls


def _truncate_no_growth(host_trees: List[HostTree], nls: np.ndarray, K: int,
                        stop_iter: int, verbosity: int
                        ) -> Tuple[List[HostTree], int]:
    """Reproduce LightGBM's stop-at-first-stump-iteration semantics post hoc
    (the loop no longer syncs per iteration to learn leaf counts live)."""
    grew = (nls.reshape(-1, K) > 1).any(axis=1)
    if grew.all():
        return host_trees, stop_iter
    first = int(np.argmax(~grew))
    if verbosity > 0:
        log.info("No further splits with positive gain; stopping at "
                 "iteration %d", first)
    return host_trees[:(first + 1) * K], min(stop_iter, first)



def _build_efb(bins, mapper, params, f, verbosity_tag=""):
    """Shared EFB setup: plan bundles, build the device expansion maps and
    the bundled host matrix.  Returns ``(efb_dev, efb_host, bundled)`` or
    ``(None, None, None)`` when bundling is trivial — callers decide the
    path-specific gate conditions."""
    from .efb import bundle_matrix, expansion_arrays, find_bundles
    nb_list = [mapper.feature_num_bins(j) for j in range(f)]
    spec = find_bundles(np.asarray(bins), nb_list, mapper.missing_bin,
                        params.max_conflict_rate,
                        max_bundle_bins=mapper.num_total_bins,
                        seed=params.seed)
    if spec.is_trivial:
        return None, None, None
    efb_host = expansion_arrays(spec, mapper.num_total_bins,
                                mapper.missing_bin)
    bundled = bundle_matrix(np.asarray(bins), spec, mapper.missing_bin)
    if params.verbosity > 0:
        log.info("EFB%s: %d features -> %d bundle columns",
                 verbosity_tag, f, spec.num_bundles)
    return _efb_dev_from_host(efb_host), efb_host, bundled


def _efb_dev_from_host(efb_host):
    """Upload the six EFB map arrays (dtypes pinned so a replay re-upload
    never retraces)."""
    return EFBArrays(
        gather_idx=jnp.asarray(efb_host[0], jnp.int32),
        valid=jnp.asarray(efb_host[1]),
        bundle_of=jnp.asarray(efb_host[2]),
        off_of=jnp.asarray(efb_host[3]),
        nb_of=jnp.asarray(efb_host[4]),
        default_of=jnp.asarray(efb_host[5]))


#: set to "0" to skip fit-time reference-profile capture (ISSUE 15) —
#: e.g. a bench run that fits thousands of throwaway models
REF_PROFILE_ENV = "MMLSPARK_TPU_REF_PROFILE"

#: rows fed to the margin sketch's representative-predict pass; the
#: per-feature sketches always count the FULL binned matrix (bincount
#: is cheap), only the margin baseline subsamples
_REF_PROFILE_MARGIN_ROWS = 32768


def _bin_representatives(mapper: BinMapper) -> List[np.ndarray]:
    """Per-feature lookup ``fine bin index -> representative raw
    value``.  Tree thresholds are bin upper bounds, so every raw value
    in fine bin ``b`` falls on the same side of every split as the
    bound ``ub[b]`` — predicting on the representatives routes to
    EXACTLY the leaves the true raw rows would (missing bin → NaN,
    which the forest walk routes via default direction; categorical
    bins → their raw category value)."""
    reps: List[np.ndarray] = []
    for j in range(mapper.num_features):
        rep = np.full(mapper.num_total_bins, np.nan, np.float64)
        if mapper.is_categorical(j):
            vals = mapper.cat_values[j]
            rep[:len(vals)] = vals.astype(np.float64)
        else:
            ub = mapper.upper_bounds[j]
            if len(ub):
                rep[:len(ub)] = ub
                rep[len(ub)] = ub[-1] + max(1.0, abs(float(ub[-1])))
            else:
                rep[0] = 0.0
        reps.append(rep)
    return reps


def _capture_reference_profile(booster: Booster, bins, mapper,
                               feature_names) -> None:
    """Attach the fit-time data-quality baseline (ISSUE 15): per-feature
    sketches over the full binned training matrix plus a
    prediction-margin sketch from a bin-representative predict pass.
    Advisory — a capture failure logs and leaves
    ``booster.reference_profile`` None (drift monitoring off), it never
    fails the fit."""
    if os.environ.get(REF_PROFILE_ENV, "1") == "0" or mapper is None:
        return
    try:
        from ..core.sketch import build_reference_profile
        if isinstance(bins, (list, tuple)):
            bins = np.concatenate([np.asarray(b) for b in bins], axis=0)
        bins = np.asarray(bins)
        if bins.ndim != 2 or bins.shape[1] != mapper.num_features:
            return
        sample = bins
        if sample.shape[0] > _REF_PROFILE_MARGIN_ROWS:
            idx = np.random.default_rng(0).choice(
                sample.shape[0], size=_REF_PROFILE_MARGIN_ROWS,
                replace=False)
            idx.sort()
            sample = sample[idx]
        reps = _bin_representatives(mapper)
        Xr = np.empty(sample.shape, np.float32)
        for j, rep in enumerate(reps):
            Xr[:, j] = rep[sample[:, j].astype(np.int64)]
        margins = np.asarray(booster.predict_margin(Xr))
        booster.reference_profile = build_reference_profile(
            bins, mapper, margins, feature_names=feature_names,
            meta={"trees": len(booster.trees),
                  "num_class": booster.num_class,
                  "fit_span": _tm.current_fit_span()})
        train_stats.incr("ref_profiles")
    except Exception:  # noqa: BLE001 - the profile is advisory
        log.exception("reference-profile capture failed; drift "
                      "monitoring will be unavailable for this model")


def train(*args, **kwargs) -> Booster:
    """Train a forest — the public entrypoint (see :func:`_train_impl`
    for the full parameter contract).

    Wraps the fit in a telemetry *fit span* (ISSUE 5): a span id is
    minted per fit and published process-globally
    (:func:`mmlspark_tpu.core.telemetry.current_fit_span`) so the
    checkpoint writer stamps it into snapshot meta and the elastic
    heartbeat stamps it into lease files; ``fit_begin`` / ``fit_end``
    (or ``fit_failed``) journal events bracket every ``boost_chunk`` /
    ``ckpt_*`` event emitted in between, which is what
    ``tools/trace_report.py`` reconstructs into a fit timeline.  A
    nested call (the sharded trainer's small-fit serial fallback) joins
    the enclosing span instead of minting its own."""
    nested = _tm.current_fit_span() is not None
    if nested:
        return _train_impl(*args, **kwargs)
    span = _tm.new_trace_id()
    _tm.set_current_fit_span(span)
    t0 = time.perf_counter()
    _tm.get_journal().emit("fit_begin", fit=span)
    try:
        booster = _train_impl(*args, **kwargs)
    except BaseException as e:
        _tm.get_journal().emit("fit_failed", fit=span,
                               error=type(e).__name__)
        if not isinstance(e, KeyboardInterrupt):
            # self-contained post-mortem: journal tail (boost_chunk /
            # ckpt_* history), metrics and thread stacks at the moment
            # the fit died — the flight record IS the crash report
            _tm.record_flight("fit_failed",
                              {"fit": span, "error": repr(e)})
        _tm.set_current_fit_span(None)
        raise
    def _arg(i: int, name: str):
        return args[i] if len(args) > i else kwargs.get(name)

    _capture_reference_profile(booster, _arg(0, "bins"),
                               _arg(3, "mapper"),
                               _arg(6, "feature_names"))
    _tm.get_journal().emit(
        "fit_end", fit=span,
        dur_s=round(time.perf_counter() - t0, 3),
        trees=len(booster.trees))
    _tm.set_current_fit_span(None)
    return booster


def train_incremental(bins: np.ndarray, labels: np.ndarray,
                      mapper: BinMapper, *, init_booster: Booster,
                      objective: Objective, params: TrainParams,
                      weights: Optional[np.ndarray] = None,
                      feature_names: Optional[List[str]] = None,
                      callbacks: Optional[List[Callable]] = None
                      ) -> Booster:
    """Continued training straight from pre-binned rows — the
    fit-from-ingest entry (ISSUE 18).

    The streaming ingest retains rows ALREADY binned to the active
    model's ladder, so the raw values are gone; but tree thresholds are
    bin upper bounds, so every raw value in a bin routes through the
    active forest exactly like the bin's representative value
    (:func:`_bin_representatives`) — the init margins computed here are
    bit-identical to what ``base.py`` would compute from the raw rows.
    The new trees boost from those margins and the returned booster is
    ``init_booster.extended(new)``, the same merged-forest contract as
    the estimator's ``initModelPath`` path.

    ``params.checkpoint_dir`` composes: the fingerprint covers
    ``init_scores``, so a fit SIGKILLed mid-boost resumes bit-identical
    from the last durable chunk (the chaos drill's kill point).
    """
    if params.boosting not in ("gbdt", "goss"):
        raise ValueError(
            "incremental training requires boosting gbdt or goss: "
            f"got {params.boosting!r}")
    if init_booster.num_class != objective.num_model_per_iteration:
        raise ValueError(
            f"init model has num_class={init_booster.num_class}, this "
            f"fit trains {objective.num_model_per_iteration}")
    if init_booster.max_feature_idx != mapper.num_features - 1:
        raise ValueError(
            f"init model was trained on "
            f"{init_booster.max_feature_idx + 1} features, the binned "
            f"matrix has {mapper.num_features}")
    bins = np.ascontiguousarray(bins)
    if bins.ndim != 2 or bins.shape[1] != mapper.num_features:
        raise ValueError(
            f"bins shape {bins.shape} does not match the mapper's "
            f"{mapper.num_features} features")
    reps = _bin_representatives(mapper)
    Xr = np.empty(bins.shape, np.float64)
    for j, rep in enumerate(reps):
        Xr[:, j] = rep[bins[:, j].astype(np.int64)]
    margins = np.asarray(init_booster.predict_margin(Xr), np.float64)
    booster = train(bins, labels, weights, mapper, objective, params,
                    feature_names, init_scores=margins,
                    callbacks=callbacks)
    merged = init_booster.extended(booster)
    # the publishable profile must describe the MERGED forest's margins
    # (the canary's drift monitor compares live margins against it)
    _capture_reference_profile(merged, bins, mapper, feature_names)
    return merged


def _train_impl(bins: np.ndarray, labels: np.ndarray,
                weights: Optional[np.ndarray],
          mapper: BinMapper, objective: Objective, params: TrainParams,
          feature_names: Optional[List[str]] = None,
          val_bins: Optional[np.ndarray] = None,
          val_labels: Optional[np.ndarray] = None,
          val_weights: Optional[np.ndarray] = None,
          val_metric: Optional[Callable] = None,
          grad_fn_override=None,
          callbacks: Optional[List[Callable]] = None,
          mesh=None,
          init_scores: Optional[np.ndarray] = None,
          val_init_scores: Optional[np.ndarray] = None,
          ranking_info: Optional[Dict] = None,
          shard_rows: Optional[List[int]] = None) -> Booster:
    """Train a forest.  ``bins``: (n, f) int32 pre-binned features.

    ``val_init_scores``: per-row margin offsets for the validation set —
    the continued-training (init_model) companion of ``init_scores``, so
    early stopping evaluates the merged model's trajectory.

    ``grad_fn_override``: optional ``(scores) -> (g, h)`` replacing the
    objective's grad/hess (used by the ranking objective which closes over
    query structure).

    ``callbacks``: each called as ``cb(it, trees_dev)`` with the list of
    on-device ``TreeArrays`` grown so far (fixed-size, shrinkage applied);
    host conversion happens once after the loop, so callbacks that need
    host trees must convert explicitly (and pay the device sync).

    ``mesh``: a ``(data, feature)`` Mesh for distributed training; rows and
    features are padded to the mesh shape and the boost step runs under
    ``shard_map`` with psum histogram allreduce (SURVEY.md §5.8 swap).

    ``bins`` may also be a LIST of per-shard binned matrices (with
    ``labels``/``weights`` lists to match) for multi-host ingestion: each
    data shard's rows go straight to its mesh slice with no global
    materialization (SURVEY.md §7 hard part 4; requires ``mesh``;
    supports validation/early stopping, per-machine bagging, callbacks,
    init scores, goss, rf, dart and lambdarank — for ranking each
    query's rows must live on one shard).
    """
    if isinstance(bins, (list, tuple)):
        return _train_distributed_sharded(
            bins, labels, weights, mapper, objective, params, mesh,
            feature_names, val_bins=val_bins, val_labels=val_labels,
            val_weights=val_weights, val_metric=val_metric,
            callbacks=callbacks,
            grad_fn_override=grad_fn_override, init_scores=init_scores,
            ranking_info=ranking_info, shard_rows=shard_rows)
    n, f = bins.shape
    K = objective.num_model_per_iteration
    rng = np.random.default_rng(params.seed)
    bag_rng = np.random.default_rng(params.bagging_seed)

    w = np.ones(n) if weights is None else np.asarray(weights, np.float64)
    objective.prepare(np.asarray(labels), w)
    # Per-row init scores (initScoreCol) replace boost_from_average, as in
    # LightGBM; they are a training-time offset not baked into the model.
    init = objective.init_score(np.asarray(labels), w) \
        if params.boost_from_average and init_scores is None else 0.0

    use_voting = params.parallelism == "voting"
    collective, mesh, coll_downgrade = _resolve_collective_cfg(
        params, mesh, ranking=ranking_info is not None)
    qbits, qmc, qwire, collective, qdown = _resolve_quantized(
        params, n, mesh, collective, ranking=ranking_info is not None)
    cfg = GrowerConfig(
        num_leaves=params.num_leaves, max_depth=params.max_depth,
        num_bins=mapper.num_total_bins, lambda_l1=params.lambda_l1,
        lambda_l2=params.lambda_l2, min_data_in_leaf=params.min_data_in_leaf,
        min_sum_hessian_in_leaf=params.min_sum_hessian_in_leaf,
        min_gain_to_split=params.min_gain_to_split,
        hist_method=_resolve_hist_method(params.histogram_method),
        packed_gather=params.packed_gather,
        collective=collective,
        voting_k=params.top_k if use_voting else 0,
        use_categorical=mapper.has_categorical,
        cat_smooth=params.cat_smooth, cat_l2=params.cat_l2,
        max_cat_threshold=params.max_cat_threshold,
        max_cat_to_onehot=params.max_cat_to_onehot,
        quantized_bits=qbits, quantized_seed=params.seed,
        quantized_max_code=qmc, quantized_wire=qwire)
    coll_sched = _collective_sched_for(cfg, mesh, n, f)
    _record_fit_resolution(cfg, collective, coll_downgrade, coll_sched,
                           quantized_downgrade=qdown)

    if params.boosting not in ("gbdt", "goss", "dart", "rf"):
        raise NotImplementedError(
            f"boostingType={params.boosting!r} is not supported; "
            "use 'gbdt', 'goss', 'dart' or 'rf'")
    use_goss = params.boosting == "goss"
    use_dart = params.boosting == "dart"
    use_rf = params.boosting == "rf"
    if use_rf:
        if not (params.bagging_freq > 0 and
                0.0 < params.bagging_fraction < 1.0):
            raise ValueError(
                "boostingType='rf' requires bagging: set "
                "baggingFraction in (0,1) and baggingFreq > 0 "
                "(as in LightGBM)")

    if use_dart:
        if params.early_stopping_round > 0:
            raise NotImplementedError(
                "boostingType='dart' does not support early stopping "
                "(dropped-tree rescaling is not invertible by truncation); "
                "unset earlyStoppingRound")
    if use_goss:
        if params.bagging_freq > 0 and params.bagging_fraction < 1.0:
            raise ValueError("Cannot use bagging in GOSS "
                             "(as in LightGBM); unset baggingFraction/"
                             "baggingFreq or use boostingType='gbdt'")
        if not (0.0 < params.top_rate < 1.0 and
                0.0 < params.other_rate < 1.0) or \
                params.top_rate + params.other_rate >= 1.0:
            raise ValueError("GOSS needs 0 < topRate < 1, "
                             "0 < otherRate < 1 and topRate + otherRate "
                             f"< 1, got {params.top_rate}/"
                             f"{params.other_rate}")
        k1 = max(1, int(np.ceil(n * params.top_rate)))
        k2 = max(1, int(np.ceil(n * params.other_rate)))
        if k1 + k2 >= n:
            use_goss = False   # rounding on tiny n: nothing to shrink
            if params.verbosity > 0:
                log.info("GOSS sample covers every row (n=%d); training "
                         "falls back to plain gbdt", n)
        else:
            goss_amp = (1.0 - params.top_rate) / params.other_rate
            goss_keys = jax.random.split(
                jax.random.PRNGKey(params.bagging_seed),
                params.num_iterations)

    use_mesh = mesh is not None and int(np.prod(
        [mesh.shape[a] for a in mesh.axis_names])) > 1
    # scale guard (BASELINE config 5): estimate per-device HBM before the
    # first compile and fail fast with remediation if the fit can't fit
    from .budget import check_fit_budget
    _dn = (int(mesh.shape["data"]) if use_mesh else 1)
    _bagging = params.bagging_freq > 0 and params.bagging_fraction < 1.0
    # model the chunk the loop will ACTUALLY use: with nothing forcing a
    # host sync the whole fit is ONE scan stacking T*K trees on device
    _chunk = params.num_iterations
    if _bagging:
        _chunk = min(_chunk, 64)
    if val_bins is not None:
        _chunk = min(_chunk, 64)
    if callbacks:
        _chunk = min(_chunk, 8)
    if params.fault_tolerant_retries > 0:
        _chunk = min(_chunk, 32)
    if params.checkpoint_dir:
        _chunk = min(_chunk, max(1, params.checkpoint_chunk))
    check_fit_budget(
        n_local=-(-n // _dn), num_features=f,
        num_bins=mapper.num_total_bins, num_leaves=params.num_leaves,
        num_class=K, chunk=_chunk,
        bin_itemsize=np.dtype(mapper.bin_dtype).itemsize,
        bagging=_bagging,
        n_val_local=(-(-val_bins.shape[0] // _dn)
                     if val_bins is not None else 0),
        data_shards=_dn, verbosity=params.verbosity)
    if use_mesh:
        if ranking_info is not None:
            if init_scores is not None:
                raise NotImplementedError(
                    "per-row init scores (initScoreCol, or the margins "
                    "of an initModelPath continuation) are not "
                    "supported with a MESH ranking objective — the "
                    "packed-query scan boots from zero like LightGBM's "
                    "lambdarank; continue a ranker serially, or train "
                    "fresh under the mesh")
            if callbacks:
                raise NotImplementedError(
                    "per-iteration callbacks are not supported with "
                    "mesh lambdarank (the ranking scan keeps trees on "
                    "device between chunks); drop the callbacks or "
                    "train without a mesh")
            return _train_distributed_ranking(
                bins, labels, w, mapper, objective, params, cfg, mesh,
                feature_names, init, rng, ranking_info,
                val_bins=val_bins, val_labels=val_labels,
                val_weights=val_weights, val_metric=val_metric)
        if grad_fn_override is not None:
            raise NotImplementedError(
                "custom gradient overrides are not supported with a "
                "mesh (only lambdarank, which provides ranking_info)")
        if use_dart:
            return _train_distributed_dart(
                bins, labels, w, mapper, objective, params, cfg, mesh,
                feature_names, init, rng, bag_rng, init_scores,
                val_bins=val_bins, val_labels=val_labels,
                val_weights=val_weights, val_metric=val_metric,
                callbacks=callbacks)
        return _train_distributed(
            bins, labels, w, mapper, objective, params, cfg, mesh,
            feature_names, init, rng, bag_rng, init_scores,
            val_bins=val_bins, val_labels=val_labels,
            val_weights=val_weights, val_metric=val_metric,
            callbacks=callbacks, val_init_scores=val_init_scores)

    # Exclusive Feature Bundling (serial paths; uint8 bins only — a
    # bundle's encoded width is capped at num_total_bins).  goss/dart
    # score the bundled TRAINING matrix through the EFB-aware walk
    # (predict_tree_binned_efb decodes each level's bundle column back
    # to the node's original feature); the ranking host loop
    # (grad_fn_override) stays unbundled.
    efb_dev = None
    bins_host_final = bins
    if params.enable_bundle and not mapper.has_categorical \
            and mapper.num_total_bins <= 256 and grad_fn_override is None:
        efb_dev, efb_host, bundled = _build_efb(bins, mapper, params, f)
        if efb_dev is not None:
            bins_host_final = bundled
    bins_d = jnp.asarray(bins_host_final, mapper.bin_dtype)
    labels_d = jnp.asarray(labels,
                           jnp.int32 if K > 1 else jnp.float32)
    weights_d = jnp.asarray(w, jnp.float32)
    scores0 = np.full((n, K) if K > 1 else (n,), init, np.float32)
    if init_scores is not None:
        iscores = np.asarray(init_scores, np.float32)
        scores0 = scores0 + (iscores if scores0.ndim == iscores.ndim
                             else iscores[:, None])
    scores = jnp.asarray(scores0)

    has_val = val_bins is not None and val_metric is not None
    if has_val:
        val_bins_d = jnp.asarray(val_bins, mapper.bin_dtype)
        vs0 = np.full(
            (val_bins.shape[0], K) if K > 1 else (val_bins.shape[0],),
            init, np.float32)
        if val_init_scores is not None:
            vsc = np.asarray(val_init_scores, np.float32)
            vs0 = vs0 + (vsc if vs0.ndim == vsc.ndim else vsc[:, None])
        val_scores = jnp.asarray(vs0)
        val_labels_np = np.asarray(val_labels)
    else:
        val_bins_d = jnp.zeros((1, f), mapper.bin_dtype)
        val_scores = jnp.zeros((1, K) if K > 1 else (1,), jnp.float32)
    best_metric, best_iter = np.inf, -1

    fi_base = _feat_info_from_mapper(mapper, f)
    T = params.num_iterations
    esr = params.early_stopping_round
    use_bag = params.bagging_freq > 0 and params.bagging_fraction < 1.0
    use_ff = params.feature_fraction < 1.0
    cur_bag = np.ones(n, np.float32)

    def iter_fi(_gi):
        """Per-iteration feature-fraction mask (serial draw order)."""
        if not use_ff:
            return fi_base
        return _draw_feature_fraction(rng, fi_base, f,
                                      params.feature_fraction)

    # Chunking: iterations run on-device in lax.scan chunks; the host only
    # syncs between chunks, where early stopping and callbacks live.  With
    # no per-iteration host decision the whole fit is ONE launch.
    if has_val:
        # bounded regardless of esr: the scan stacks (chunk, n_val[, K])
        # per-iteration val scores, which must not grow with T or esr
        # (best_iter persists across chunks, so stopping stays correct)
        chunk = min(T, max(min(esr, 64), 8) if esr > 0 else 64)
    elif callbacks:
        chunk = min(T, 8)
    else:
        chunk = T
    if use_bag:
        # bag_masks are (chunk, n): bound the chunk so per-fit device
        # memory stays O(n), not O(T*n)
        chunk = min(chunk, 64)
    if params.fault_tolerant_retries > 0:
        # bounded chunks = bounded replay work after a device failure;
        # host copies of the training inputs make full re-upload possible
        # when a failure kills every device buffer
        chunk = min(chunk, 32)
        ft_host = {
            "bins": np.asarray(bins_host_final),
            "labels": np.asarray(labels),
            "w": np.asarray(w),
            "val_bins": np.asarray(val_bins_d),
        }
    ckpt = params.checkpoint_dir
    if ckpt and (use_dart or grad_fn_override is not None):
        log.warning("checkpoint_dir is inert for dart/custom-gradient "
                    "host loops (per-iteration host bookkeeping; no "
                    "chunk boundaries to snapshot)")
        ckpt = ""
    if ckpt:
        # bounded chunks = bounded lost work after a process death
        chunk = min(chunk, max(1, params.checkpoint_chunk))
        ckpt_fp = _ckpt_fingerprint(n, f, K, params, labels, bins, w,
                                    init_scores)

    trees_chunks: List[TreeArrays] = []
    stop_iter = T

    if grad_fn_override is not None and not use_dart:
        # Per-iteration host loop: the ranking gradient closes over query
        # structure on the host (not a hashable static), so it can't ride
        # the scan.  Trees still cross to the host as one packed chunk.
        # goss samples inside the loop (Σ|g·h| ranking per iteration); rf
        # fits every tree at the constant init scores, unshrunk.
        run_grow = _debug.checked(functools.partial(grow_tree, cfg=cfg))
        binsT_d = jnp.transpose(bins_d)   # fit-invariant, once per fit
        trees_list: List[TreeArrays] = []
        for it in range(T):
            t_iter = time.perf_counter()
            if use_bag and it % params.bagging_freq == 0:
                cur_bag = (bag_rng.random(n) < params.bagging_fraction
                           ).astype(np.float32)
            bag_mask = jnp.asarray(cur_bag)
            fi = jnp.asarray(iter_fi(it))
            g, h = grad_fn_override(scores)
            if use_goss:
                infl = jnp.abs(g * h)
                rank = jnp.argsort(-infl)
                top_idx = rank[:k1]
                rk = jax.random.uniform(goss_keys[it], (n - k1,))
                other_idx = jnp.take(rank[k1:], jnp.argsort(rk)[:k2])
                idx = jnp.concatenate([top_idx, other_idx])
                amp_vec = jnp.concatenate([
                    jnp.ones(k1, jnp.float32),
                    jnp.full(k2, goss_amp, jnp.float32)])
                gh = jnp.stack([jnp.take(g, idx) * amp_vec,
                                jnp.take(h, idx) * amp_vec,
                                jnp.ones(k1 + k2, jnp.float32)], axis=1)
                tree, _ = run_grow(jnp.take(bins_d, idx, axis=0), gh, fi)
                scores = scores + params.learning_rate * \
                    predict_tree_binned(tree, bins_d, params.num_leaves)
                tree = apply_shrinkage(tree, params.learning_rate)
                trees_list.append(tree)
            else:
                gh = jnp.stack([g * bag_mask, h * bag_mask, bag_mask],
                               axis=1)
                tree, row_leaf = run_grow(bins_d, gh, fi, binsT=binsT_d)
                if not use_rf:
                    scores = scores + params.learning_rate * \
                        tree.leaf_value[row_leaf]
                    tree = apply_shrinkage(tree, params.learning_rate)
                trees_list.append(tree)
            # per-iteration telemetry (custom-gradient host loop):
            # objective=None — the override replaces the objective's
            # gradient, so its train_loss would not describe this fit
            get_profiler().record_phase(
                "train.host_iter", time.perf_counter() - t_iter)
            _monitor_chunk(it, it + 1, time.perf_counter() - t_iter,
                           n, K, cfg.hist_method, coll_sched=coll_sched)
            if has_val:
                # trees are already shrunk, so val scores add at lr=1.0
                val_scores = val_scores + predict_tree_binned(
                    tree, val_bins_d, params.num_leaves)
                margins = (_rf_margins(init, np.asarray(val_scores), it)
                           if use_rf else np.asarray(val_scores))
                metric = float(val_metric(margins, val_labels_np,
                                          val_weights))
                if metric < best_metric - 1e-12:
                    best_metric, best_iter = metric, it
                elif esr > 0 and it - best_iter >= esr:
                    if params.verbosity > 0:
                        log.info("Early stopping at iteration %d "
                                 "(best %d, metric %.6f)", it, best_iter,
                                 best_metric)
                    stop_iter = best_iter + 1
                    trees_list = trees_list[:stop_iter]
                    break
            if callbacks:
                for cb in callbacks:
                    cb(it, trees_list)
        if trees_list:
            trees_chunks = [jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *trees_list)]
    elif use_dart:
        # Dart (Rashmi & Gilad-Bachrach 2015; LightGBM boosting=dart):
        # each iteration drops a random subset of the ensemble, fits the
        # new tree against the dropped-out scores, then renormalizes —
        # the new tree joins at weight 1/(k+1) and the k dropped trees
        # shrink by k/(k+1), preserving the ensemble total.  Per-tree
        # weights are tracked on host and baked into the exported trees.
        dart_rng = np.random.default_rng(params.drop_seed)
        run_dart = _debug.checked(functools.partial(
            _dart_step, obj=objective, cfg=cfg, lr=params.learning_rate,
            K=K, efb=efb_dev))
        run_grow_dart = _debug.checked(functools.partial(grow_tree,
                                                         cfg=cfg))
        binsT_d = jnp.transpose(bins_d)   # fit-invariant, once per fit
        L_steps = params.num_leaves

        def unit_margin(unit, b, efb=None):
            """One dart unit's contribution: a tree (K=1) or the stacked
            K class trees of one iteration (dart drops whole iterations,
            as LightGBM does).  ``efb`` must match THE MATRIX ``b``: the
            training matrix is bundled under EFB, the validation matrix
            never is — callers pass efb_dev only with bins_d."""
            if K == 1:
                return predict_tree_binned_any(unit, b, L_steps, efb,
                                               cfg.num_bins)
            return _dart_iter_margin(unit, b, L_steps, efb=efb,
                                     num_bins=cfg.num_bins)

        bag_state = {"cur": np.ones(n, np.float32)}

        def bag_draw(it):
            if use_bag and it % params.bagging_freq == 0:
                bag_state["cur"] = (
                    bag_rng.random(n) < params.bagging_fraction
                ).astype(np.float32)
            return jnp.asarray(bag_state["cur"])

        def fi_draw(it):
            return jnp.asarray(iter_fi(it))

        def grow_unit(s_minus, bag_mask, fi):
            if grad_fn_override is not None:
                # ranking dart (single-model): gradients at the dropped-
                # out scores through the query-structured closure
                g, h = grad_fn_override(s_minus)
                gh = jnp.stack([g * bag_mask, h * bag_mask, bag_mask],
                               axis=1)
                unit, row_leaf = run_grow_dart(bins_d, gh, fi,
                                               binsT=binsT_d)
                unit = apply_shrinkage(unit, params.learning_rate)
                return unit, unit.leaf_value[row_leaf]
            return run_dart(bins_d, binsT_d, s_minus, labels_d,
                            weights_d, bag_mask, fi)

        val_state = {"scores": val_scores if has_val else None,
                     "best": (np.inf, -1)}

        def val_hook(it, unit, sel, scales_pre, norm):
            if not has_val:
                return
            vs = val_state["scores"]
            if len(sel):
                P_val = scales_pre[sel[0]] * unit_margin(
                    units_ref[sel[0]], val_bins_d)
                for i in sel[1:]:
                    P_val = P_val + scales_pre[i] * unit_margin(
                        units_ref[i], val_bins_d)
                vs = vs - norm * P_val
            vs = vs + norm * unit_margin(unit, val_bins_d)
            val_state["scores"] = vs
            metric = float(val_metric(np.asarray(vs), val_labels_np,
                                      val_weights))
            best, bi = val_state["best"]
            if metric < best - 1e-12:
                val_state["best"] = (metric, it)

        # the hook needs the unit list the loop is building
        units_ref: List[TreeArrays] = []
        units, trees_list, scales, scores = _dart_host_loop(
            T, K, dart_rng, params, scores, bag_draw, fi_draw, grow_unit,
            lambda u: unit_margin(u, bins_d, efb_dev), callbacks,
            val_hook=val_hook if has_val else None, units_out=units_ref)
        if trees_list:
            trees_chunks = [jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *trees_list)]
    else:
        # debug/sanitizer mode (SURVEY.md §5.2): checkified variants raise
        # on OOB indexing or non-finite gradients instead of training
        # silently on garbage; identity wrappers when debug mode is off.
        # Static args bind via partial so checkify only sees array args.
        run_scan = _debug.checked(functools.partial(
            _boost_scan, obj=objective, cfg=cfg, lr=params.learning_rate,
            has_val=has_val, rf=use_rf, efb=efb_dev))
        if use_goss:
            run_goss = _debug.checked(functools.partial(
                _boost_scan_goss, obj=objective, cfg=cfg,
                lr=params.learning_rate, k1=k1, k2=k2, amp=goss_amp,
                has_val=has_val, K=K, efb=efb_dev))
        if K > 1:
            run_multi = _debug.checked(functools.partial(
                _boost_scan_multi, obj=objective, cfg=cfg,
                lr=params.learning_rate, K=K, has_val=has_val,
                efb=efb_dev, rf=use_rf))
        cb_list: List[TreeArrays] = []
        it = 0
        if ckpt:
            snap = _ckpt_load(ckpt, ckpt_fp)
            if snap is None:
                # purge any stale snapshot files: the write-once chunk
                # files of an abandoned fit must not be skipped-over by
                # this run's saves and then stitched into ITS meta
                _ckpt_clear(ckpt)
            else:
                train_stats.incr("ckpt_resumed")
                _ckpt_event("ckpt_resumed", it=int(snap["it"]))
                it = snap["it"]
                trees_chunks = list(snap["trees_chunks"])
                scores = jnp.asarray(snap["scores"])
                val_scores = jnp.asarray(snap["val_scores"])
                cur_bag = np.asarray(snap["cur_bag"], np.float32)
                rng.bit_generator.state = snap["rng_state"]
                bag_rng.bit_generator.state = snap["bag_rng_state"]
                best_metric = snap["best_metric"]
                best_iter = snap["best_iter"]
                if callbacks:
                    log.warning("resuming from checkpoint at iteration "
                                "%d: callbacks replay only for the "
                                "remaining iterations", it)
                elif params.verbosity > 0:
                    log.info("resuming from checkpoint at iteration %d",
                             it)
        while it < T:
            C = min(chunk, T - it)
            if use_bag:
                rows = []
                for j in range(C):
                    if (it + j) % params.bagging_freq == 0:
                        cur_bag = (bag_rng.random(n) <
                                   params.bagging_fraction
                                   ).astype(np.float32)
                    rows.append(cur_bag)
                bag_masks = jnp.asarray(np.stack(rows))
            else:
                bag_masks = jnp.ones((C, 1), jnp.float32)
            if use_ff:
                fi_stack = jnp.asarray(
                    np.stack([iter_fi(it + j) for j in range(C)]))
            else:
                fi_stack = jnp.asarray(np.broadcast_to(
                    fi_base, (C,) + fi_base.shape))
            def run_chunk(scores, val_scores):
                if use_goss:
                    return run_goss(
                        bins_d, scores, labels_d, weights_d,
                        goss_keys[it:it + C], fi_stack, val_bins_d,
                        val_scores)
                if K > 1:
                    return run_multi(
                        bins_d, scores, labels_d, weights_d, bag_masks,
                        fi_stack, val_bins_d, val_scores)
                return run_scan(
                    bins_d, scores, labels_d, weights_d, bag_masks,
                    fi_stack, val_bins_d, val_scores)

            t_chunk = time.perf_counter()
            ftr = params.fault_tolerant_retries
            if ftr > 0:
                # chunk-boundary snapshots + replay (SURVEY.md §5.3): a
                # device/tunnel failure may take EVERY device buffer with
                # it, so a replay re-uploads all chunk inputs from host
                # copies (ft_host snapshot taken before the loop, plus
                # this chunk's already-drawn masks) — the replayed chunk
                # is bit-identical to the one that failed.
                snap = (np.asarray(scores), np.asarray(val_scores))
                bagm_host = np.asarray(bag_masks)
                fi_host = np.asarray(fi_stack)
                for attempt in range(ftr + 1):
                    try:
                        trees_st, scores, val_scores, val_hist = run_chunk(
                            jnp.asarray(snap[0]), jnp.asarray(snap[1]))
                        # materialize: a failure discovered later must not
                        # invalidate this chunk's results
                        jax.block_until_ready(trees_st)
                        break
                    except Exception as e:  # noqa: BLE001 - device loss
                        from jax.experimental import checkify as _ck
                        if isinstance(e, _ck.JaxRuntimeError):
                            raise  # deterministic sanitizer error: a
                            # replay would fail identically
                        if attempt >= ftr:
                            raise
                        if efb_dev is not None:
                            # the EFB maps are device buffers too — dead
                            # after a device loss; re-upload and rebind
                            # the chunk runners that captured them
                            efb_dev = _efb_dev_from_host(efb_host)
                            run_scan = _debug.checked(functools.partial(
                                _boost_scan, obj=objective, cfg=cfg,
                                lr=params.learning_rate, has_val=has_val,
                                rf=use_rf, efb=efb_dev))
                            if K > 1:
                                run_multi = _debug.checked(
                                    functools.partial(
                                        _boost_scan_multi, obj=objective,
                                        cfg=cfg, lr=params.learning_rate,
                                        K=K, has_val=has_val,
                                        efb=efb_dev, rf=use_rf))
                        train_stats.incr("chunks_replayed")
                        _ckpt_event("chunk_replayed", it=int(it),
                                    attempt=attempt + 1)
                        log.warning(
                            "chunk at iteration %d failed (attempt %d/%d);"
                            " re-uploading state and replaying",
                            it, attempt + 1, ftr)
                        bins_d = jnp.asarray(ft_host["bins"],
                                             mapper.bin_dtype)
                        labels_d = jnp.asarray(
                            ft_host["labels"],
                            jnp.int32 if K > 1 else jnp.float32)
                        weights_d = jnp.asarray(ft_host["w"], jnp.float32)
                        val_bins_d = jnp.asarray(ft_host["val_bins"],
                                                 mapper.bin_dtype)
                        bag_masks = jnp.asarray(bagm_host)
                        fi_stack = jnp.asarray(fi_host)
                        if use_goss:
                            goss_keys = jax.random.split(
                                jax.random.PRNGKey(params.bagging_seed),
                                params.num_iterations)
            else:
                # profiler dispatch bracketing (ISSUE 12): host glue
                # until the jitted chunk returns vs device wait until
                # its results materialize, with the compile-seq delta
                # classifying the dispatch as cache hit or miss
                _p = get_profiler()
                _seq0 = _p.compile_seq()
                trees_st, scores, val_scores, val_hist = run_chunk(
                    scores, val_scores)
                _t_host = time.perf_counter()
                # sync for honest chunk timing; the host needs these
                # results before the next chunk (or the final fetch)
                # anyway, so this moves a wait, it does not add one
                jax.block_until_ready(trees_st)
                _t_done = time.perf_counter()
                _p.dispatch("train.boost_chunk", _t_host - t_chunk,
                            _t_done - _t_host,
                            _p.compile_seq() - _seq0)
                _p.span("train.boost_chunk", _t_done - t_chunk,
                        journal=True, it=int(it),
                        host_ms=round((_t_host - t_chunk) * 1e3, 3),
                        device_ms=round((_t_done - _t_host) * 1e3, 3))
            trees_chunks.append(trees_st)
            _monitor_chunk(it, it + C, time.perf_counter() - t_chunk,
                           n, K, cfg.hist_method, objective, scores,
                           labels, w, coll_sched=coll_sched)
            stop = False
            if has_val:
                vh = np.asarray(val_hist)        # (C, n_val[, K])
                for j in range(C):
                    margins = (_rf_margins(init, vh[j], it + j)
                               if use_rf else vh[j])
                    metric = float(val_metric(margins, val_labels_np,
                                              val_weights))
                    gi = it + j
                    if metric < best_metric - 1e-12:
                        best_metric, best_iter = metric, gi
                    elif esr > 0 and gi - best_iter >= esr:
                        if params.verbosity > 0:
                            log.info("Early stopping at iteration %d "
                                     "(best %d, metric %.6f)", gi,
                                     best_iter, best_metric)
                        stop_iter = best_iter + 1
                        stop = True
                        break
            if callbacks:
                upto = stop_iter if stop else it + C
                for j in range(upto - it):
                    for k in range(K):
                        cb_list.append(jax.tree_util.tree_map(
                            lambda a, j=j, k=k: a[j * K + k], trees_st))
                    for cb in callbacks:
                        cb(it + j, cb_list)
            if stop:
                break
            it += C
            if ckpt and it < T:
                # it == T would snapshot state the very next statement
                # clears; a crash in that window just replays the final
                # chunk from the previous boundary
                _ckpt_save(ckpt, ckpt_fp, it, trees_chunks, scores,
                           val_scores, cur_bag, rng, bag_rng,
                           best_metric, best_iter)
        if ckpt:
            _ckpt_clear(ckpt)

    trees, nls = _fetch_host_trees(trees_chunks, params.num_leaves, mapper)
    trees, nls = trees[:stop_iter * K], nls[:stop_iter * K]
    trees, stop_iter = _truncate_no_growth(trees, nls, K, stop_iter,
                                           params.verbosity)
    if use_dart:
        # bake the final dart weights into the exported trees (one scale
        # per ITERATION, shared by its K class trees)
        for t, s in zip(trees, np.repeat(scales, K)):
            t.leaf_value = t.leaf_value * s
            t.internal_value = t.internal_value * s
            t.shrinkage = s
    elif use_rf:
        _rf_average_trees(trees, K)
    return _finalize_booster(trees, K, init, params, objective, mapper,
                             feature_names, f, stop_iter)


def _train_distributed_sharded(bins_shards, label_shards, weight_shards,
                               mapper, objective, params, mesh,
                               feature_names, val_bins=None, val_labels=None,
                               val_weights=None, val_metric=None,
                               callbacks=None, grad_fn_override=None,
                               init_scores=None, ranking_info=None,
                               shard_rows=None) -> Booster:
    """Multi-host mesh training from per-shard inputs: each data shard's
    rows feed its own mesh slice via ``make_array_from_callback`` — the
    full binned matrix never exists on one host (SURVEY.md §7 hard part
    4; the reference's per-executor Dataset construction).

    Supports the full chunked mesh loop via ``_train_distributed``'s
    ``shard_data`` path: validation/early stopping (the validation set is
    assumed host-small and arrives monolithic), per-machine bagging,
    callbacks (non-ranking), per-shard init scores (non-ranking), goss,
    rf, dart (any mesh layout) and lambdarank (each query pinned to the
    shard holding its rows — ranking.shard_queries_from_shards),
    including dart×ranking (the dart host loop runs on the packed
    per-shard layout; bag masks scatter through the query-pack
    permutation).  Still gated: callbacks/init-scores×ranking and
    custom gradient overrides.
    ``init_scores`` may be a per-shard LIST or one array in
    shard-concatenation order; ``ranking_info['query_ids']`` may be a
    per-shard list or one array in shard-concatenation order."""
    if mesh is None:
        raise ValueError("sharded input requires a mesh (setMesh or "
                         "multi-device default)")
    if grad_fn_override is not None:
        raise NotImplementedError(
            "custom gradient overrides are not supported with sharded "
            "ingestion (the override closes over monolithic rows); "
            "rankers pass structured ranking_info instead")
    if any(b is None for b in bins_shards):
        # multi-controller: each controller passes None for slots other
        # hosts own; shard_rows (tiny global metadata) sizes them, and
        # the 1-D label/weight lists must be COMPLETE on every
        # controller (global objective statistics need them; they are
        # metadata-sized next to bins)
        if shard_rows is None:
            raise ValueError(
                "multi-controller sharded training (None bins slots) "
                "requires shard_rows — the global per-shard row counts")
        if any(y is None for y in label_shards):
            raise ValueError(
                "label_shards must be complete on every controller "
                "(labels are 1-D metadata; allgather them, e.g. "
                "jax.experimental.multihost_utils.process_allgather)")
    K = objective.num_model_per_iteration
    rng = np.random.default_rng(params.seed)
    bag_rng = np.random.default_rng(params.bagging_seed)
    if weight_shards is None:
        weight_shards = [None if y is None else
                         np.ones(len(y), np.float64)
                         for y in label_shards]
    sizes = (list(shard_rows) if shard_rows is not None
             else [b.shape[0] for b in bins_shards])
    if any(w is None for w in weight_shards):
        raise ValueError(
            "weight_shards must be complete on every controller (1-D "
            "metadata, like labels)")
    # objective statistics need the global label/weight vectors — 1-D and
    # tiny relative to bins, which is what must never be concatenated
    y_global = np.concatenate([np.asarray(y) for y in label_shards])
    w_global = np.concatenate([np.asarray(w) for w in weight_shards])
    objective.prepare(y_global, w_global)
    if init_scores is not None:
        if isinstance(init_scores, (list, tuple)):
            init_score_shards = list(init_scores)
        else:
            offs = np.cumsum([0] + sizes)
            init_score_shards = [
                np.asarray(init_scores)[offs[d]:offs[d + 1]]
                for d in range(len(sizes))]
    else:
        init_score_shards = None
    init = objective.init_score(y_global, w_global) \
        if params.boost_from_average and init_scores is None else 0.0

    collective, mesh, coll_downgrade = _resolve_collective_cfg(
        params, mesh, ranking=ranking_info is not None)
    qbits, qmc, qwire, collective, qdown = _resolve_quantized(
        params, sum(sizes), mesh, collective,
        ranking=ranking_info is not None)
    cfg = GrowerConfig(
        num_leaves=params.num_leaves, max_depth=params.max_depth,
        num_bins=mapper.num_total_bins, lambda_l1=params.lambda_l1,
        lambda_l2=params.lambda_l2, min_data_in_leaf=params.min_data_in_leaf,
        min_sum_hessian_in_leaf=params.min_sum_hessian_in_leaf,
        min_gain_to_split=params.min_gain_to_split,
        hist_method=_resolve_hist_method(params.histogram_method),
        packed_gather=params.packed_gather,
        collective=collective,
        voting_k=params.top_k if params.parallelism == "voting" else 0,
        use_categorical=mapper.has_categorical,
        cat_smooth=params.cat_smooth, cat_l2=params.cat_l2,
        max_cat_threshold=params.max_cat_threshold,
        max_cat_to_onehot=params.max_cat_to_onehot,
        quantized_bits=qbits, quantized_seed=params.seed,
        quantized_max_code=qmc, quantized_wire=qwire)

    from .budget import check_fit_budget
    f_sh = next(b.shape[1] for b in bins_shards if b is not None)
    _record_fit_resolution(
        cfg, collective, coll_downgrade,
        _collective_sched_for(cfg, mesh, sum(sizes), f_sh),
        quantized_downgrade=qdown)
    _bagging = params.bagging_freq > 0 and params.bagging_fraction < 1.0
    _chunk = params.num_iterations
    if _bagging:
        _chunk = min(_chunk, 64)
    if val_bins is not None:
        _chunk = min(_chunk, 64)
    if callbacks:
        _chunk = min(_chunk, 8)
    if params.fault_tolerant_retries > 0:
        _chunk = min(_chunk, 32)
    if params.checkpoint_dir:
        _chunk = min(_chunk, max(1, params.checkpoint_chunk))
    check_fit_budget(
        n_local=max(sizes), num_features=f_sh,
        num_bins=mapper.num_total_bins, num_leaves=params.num_leaves,
        num_class=K, chunk=_chunk,
        bin_itemsize=np.dtype(mapper.bin_dtype).itemsize,
        bagging=_bagging,
        n_val_local=(-(-val_bins.shape[0] // int(mesh.shape["data"]))
                     if val_bins is not None else 0),
        data_shards=int(mesh.shape["data"]), verbosity=params.verbosity)
    shard_data = {"bins_shards": list(bins_shards),
                  "label_shards": list(label_shards),
                  "weight_shards": list(weight_shards),
                  "sizes": sizes,
                  "shard_rows": shard_rows,
                  "init_score_shards": init_score_shards}
    if ranking_info is not None:
        if init_score_shards is not None:
            raise NotImplementedError(
                "per-row init scores (initScoreCol, or the margins of "
                "an initModelPath continuation) are not supported with "
                "a mesh ranking objective (the packed-query scan boots "
                "from zero, as LightGBM's lambdarank does)")
        if callbacks:
            raise NotImplementedError(
                "per-iteration callbacks are not supported with mesh "
                "lambdarank (the ranking scan keeps trees on device "
                "between chunks)")
        qids = ranking_info["query_ids"]
        if isinstance(qids, (list, tuple)):
            if any(q is None for q in qids):
                raise ValueError(
                    "qid shards must be complete on every controller "
                    "(1-D metadata, like labels)")
            qid_shards = [np.asarray(q) for q in qids]
        else:
            offs = np.cumsum([0] + sizes)
            qid_shards = [np.asarray(qids)[offs[d]:offs[d + 1]]
                          for d in range(len(sizes))]
        shard_data["qid_shards"] = qid_shards
        return _train_distributed_ranking(
            None, None, None, mapper, objective, params, cfg, mesh,
            feature_names, init, rng, ranking_info,
            val_bins=val_bins, val_labels=val_labels,
            val_weights=val_weights, val_metric=val_metric,
            shard_data=shard_data)
    if params.boosting == "dart":
        return _train_distributed_dart(
            None, None, None, mapper, objective, params, cfg, mesh,
            feature_names, init, rng, bag_rng, None,
            val_bins=val_bins, val_labels=val_labels,
            val_weights=val_weights, val_metric=val_metric,
            callbacks=callbacks, shard_data=shard_data)
    return _train_distributed(
        None, None, None, mapper, objective, params, cfg, mesh,
        feature_names, init, rng, bag_rng,
        val_bins=val_bins, val_labels=val_labels,
        val_weights=val_weights, val_metric=val_metric,
        callbacks=callbacks, shard_data=shard_data)


def _train_distributed_ranking(bins, labels, w, mapper, objective, params,
                               cfg, mesh, feature_names, init, rng,
                               ranking_info, val_bins=None, val_labels=None,
                               val_weights=None, val_metric=None,
                               shard_data=None) -> Booster:
    """Mesh-sharded lambdarank: whole queries are packed per data shard
    (ranking.shard_queries), pairwise gradients stay shard-local, tree
    growth is data-parallel psum — the distributed MSLR configuration
    (SURVEY.md §3.1; BASELINE config 5).

    With ``shard_data`` (sharded ingestion), each query is pinned to the
    shard whose host holds its rows (ranking.shard_queries_from_shards)
    and the packed matrix assembles per slot via
    ``make_array_from_callback`` — no global materialization."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..core.mesh import DATA_AXIS, FEATURE_AXIS, pad_to_multiple
    from .distributed import make_ranking_scan
    from .ranking import shard_queries

    if shard_data is None:
        n, f = bins.shape
    else:
        n = int(sum(shard_data["sizes"]))
        f = next(b.shape[1] for b in shard_data["bins_shards"]
                 if b is not None)
    T = params.num_iterations
    esr = params.early_stopping_round
    use_ff = params.feature_fraction < 1.0
    use_bag = params.bagging_freq > 0 and params.bagging_fraction < 1.0
    use_rf_rk = params.boosting == "rf"
    bag_rng = np.random.default_rng(params.bagging_seed)
    dn = int(mesh.shape[DATA_AXIS])
    fn_shards = int(mesh.shape[FEATURE_AXIS])
    has_val = val_bins is not None and val_metric is not None
    if params.checkpoint_dir:
        log.warning("checkpoint_dir is inert for mesh lambdarank (the "
                    "packed-query scan state is not checkpointed); "
                    "restart a killed ranking fit from initModelPath")

    if shard_data is None:
        perm, real, (qidx, qmask, gains, labq, invmax) = shard_queries(
            np.asarray(labels), ranking_info["query_ids"], dn,
            ranking_info["truncation_level"])
        w_src = np.asarray(w, np.float32)
    else:
        from .ranking import shard_queries_from_shards
        if len(shard_data["bins_shards"]) != dn:
            raise ValueError(
                f"need one shard slot per data-mesh slice: got "
                f"{len(shard_data['bins_shards'])} slots for data={dn}")
        perm, real, (qidx, qmask, gains, labq, invmax), sh_offs = \
            shard_queries_from_shards(
                shard_data["label_shards"], shard_data["qid_shards"],
                ranking_info["truncation_level"])
        w_src = np.concatenate([np.asarray(ws, np.float32)
                                for ws in shard_data["weight_shards"]])
    npk = len(perm)                     # packed rows (D * S)
    valid = perm >= 0
    fp = pad_to_multiple(f, fn_shards) - f
    f_padded = f + fp
    wmul = np.zeros(npk, np.float32)
    wmul[valid] = w_src[perm[valid]]

    shard = lambda a, spec: jax.device_put(  # noqa: E731
        jnp.asarray(a), NamedSharding(mesh, spec))
    if shard_data is None:
        bins_np = np.asarray(bins, mapper.bin_dtype)
        bins_packed = np.zeros((npk, f_padded), mapper.bin_dtype)
        bins_packed[valid, :f] = bins_np[perm[valid]]
        bins_d = shard(bins_packed, P(DATA_AXIS, FEATURE_AXIS))
    else:
        # slot d's packed rows come from ITS host's local binned matrix
        # through the global perm shifted by the shard offset — the full
        # packed matrix never exists on one host (the same discipline as
        # prepare_arrays_from_shards; the callback never touches
        # non-local None slots)
        S_pk = npk // dn
        b_shards = shard_data["bins_shards"]

        def bins_cb(index):
            r0, r1, _ = index[0].indices(npk)
            c0, c1, _ = index[1].indices(f_padded)
            d = r0 // S_pk
            out = np.zeros((r1 - r0, c1 - c0), mapper.bin_dtype)
            p = perm[r0:r1]
            v = p >= 0
            src = b_shards[d]
            ce = min(c1, src.shape[1])
            if ce > c0:
                out[v, :ce - c0] = src[p[v] - sh_offs[d], c0:ce]
            return out

        bins_d = jax.make_array_from_callback(
            (npk, f_padded),
            NamedSharding(mesh, P(DATA_AXIS, FEATURE_AXIS)), bins_cb)
    scores = shard(np.full(npk, init, np.float32), P(DATA_AXIS))
    real_d = shard(real, P(DATA_AXIS))
    wmul_d = shard(wmul, P(DATA_AXIS))
    qidx_d = shard(qidx, P(DATA_AXIS, None, None))
    qmask_d = shard(qmask, P(DATA_AXIS, None, None))
    gains_d = shard(gains, P(DATA_AXIS, None, None))
    labq_d = shard(labq, P(DATA_AXIS, None, None))
    invmax_d = shard(invmax, P(DATA_AXIS, None))

    if has_val:
        nv = val_bins.shape[0]
        vrp = pad_to_multiple(nv, dn) - nv
        vb = np.asarray(val_bins, mapper.bin_dtype)
        if vrp:
            vb = np.concatenate([vb, np.zeros((vrp, f), vb.dtype)], axis=0)
        val_bins_d = shard(vb, P(DATA_AXIS, None))
        val_scores = shard(np.full(nv + vrp, init, np.float32),
                           P(DATA_AXIS))
        val_labels_np = np.asarray(val_labels)
    else:
        val_bins_d = shard(np.zeros((dn, f), mapper.bin_dtype),
                           P(DATA_AXIS, None))
        val_scores = shard(np.zeros(dn, np.float32), P(DATA_AXIS))

    fi_base = np.zeros((f_padded, 3), np.float32)
    fi_base[:f] = _feat_info_from_mapper(mapper, f)

    if params.boosting == "dart":
        from .distributed import (make_ranking_dart_step,
                                  make_tree_predict)
        if fn_shards > 1:
            raise NotImplementedError(
                "boostingType='dart' requires a data-only mesh; use "
                "parallelism='data' / feature=1")
        step_d = make_ranking_dart_step(
            mesh, cfg, params.learning_rate, ranking_info["sigma"],
            ranking_info["truncation_level"])
        pred_d = make_tree_predict(mesh, params.num_leaves)
        binsT_d = jnp.transpose(bins_d)
        dart_rng = np.random.default_rng(params.drop_seed)
        bag_sh = NamedSharding(mesh, P(DATA_AXIS))

        def _upload(mask_n):
            row = np.zeros(npk, np.float32)
            row[valid] = mask_n[perm[valid]]
            return jax.device_put(jnp.asarray(row), bag_sh)

        bag_state = {"dev": _upload(np.ones(n, np.float32))}

        def bag_draw(it):
            # upload only on redraw iterations (use_bag/bag_rng are the
            # function-level stream, shared with the chunked path)
            if use_bag and it % params.bagging_freq == 0:
                bag_state["dev"] = _upload(
                    (bag_rng.random(n) < params.bagging_fraction
                     ).astype(np.float32))
            return bag_state["dev"]

        def fi_draw(_it):
            if use_ff:
                return jnp.asarray(_draw_feature_fraction(
                    rng, fi_base, f, params.feature_fraction))
            return jnp.asarray(fi_base)

        def grow_unit(s_minus, bag, fi):
            return step_d(bins_d, binsT_d, s_minus, real_d, wmul_d,
                          qidx_d, qmask_d, gains_d, labq_d, invmax_d,
                          bag, fi)

        units, trees_list, scales, scores = _dart_host_loop(
            T, 1, dart_rng, params, scores, bag_draw, fi_draw,
            grow_unit, lambda u: pred_d(u, bins_d), None)
        chunks_d = []
        if trees_list:
            chunks_d = [jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *trees_list)]
        trees, nls = _fetch_host_trees(chunks_d, params.num_leaves,
                                       mapper)
        trees, stop_iter = _truncate_no_growth(trees, nls, 1, T,
                                               params.verbosity)
        for t_, s_ in zip(trees, scales):
            t_.leaf_value = t_.leaf_value * s_
            t_.internal_value = t_.internal_value * s_
            t_.shrinkage = s_
        return _finalize_booster(trees, 1, init, params, objective,
                                 mapper, feature_names, f, stop_iter)

    goss_rk = None
    if params.boosting == "goss":
        # per-shard GOSS over the packed rows (gradients stay full — the
        # pairwise lambdas need whole queries; only tree growth samples)
        if fn_shards > 1:
            raise NotImplementedError(
                "boostingType='goss' requires a data-only mesh; use "
                "parallelism='data' / feature=1")
        s_local = npk // dn
        k1 = max(1, int(np.ceil(s_local * params.top_rate)))
        k2 = max(1, int(np.ceil(s_local * params.other_rate)))
        if k1 + k2 < s_local:
            goss_rk = (k1, k2,
                       (1.0 - params.top_rate) / params.other_rate)
        elif params.verbosity > 0:
            log.info("GOSS sample covers every local row; mesh ranking "
                     "falls back to plain gbdt")
    step = make_ranking_scan(mesh, cfg, params.learning_rate,
                             ranking_info["sigma"],
                             ranking_info["truncation_level"], has_val,
                             goss=goss_rk, bag_sharded=use_bag,
                             rf=use_rf_rk)
    goss_keys_r = jax.random.split(
        jax.random.PRNGKey(params.bagging_seed), T)

    chunk = T
    if use_bag:
        chunk = min(chunk, 64)
    if has_val:
        chunk = min(chunk, max(min(esr, 64), 8) if esr > 0 else 64)
    chunks: List[TreeArrays] = []
    best_metric, best_iter = np.inf, -1
    stop_iter = T
    it = 0
    cur_bag = np.ones(n, np.float32)
    while it < T:
        C = min(chunk, T - it)
        if use_ff:
            fi_stack = jnp.asarray(np.stack([
                _draw_feature_fraction(rng, fi_base, f,
                                       params.feature_fraction)
                for _ in range(C)]))
        else:
            fi_stack = jnp.asarray(np.broadcast_to(fi_base,
                                                   (C,) + fi_base.shape))
        if use_bag:
            rows = []
            for j in range(C):
                if (it + j) % params.bagging_freq == 0:
                    # same stream as a serial run with this baggingSeed,
                    # drawn over ORIGINAL row order then scattered
                    # through the query-pack permutation
                    cur_bag = (bag_rng.random(n) < params.bagging_fraction
                               ).astype(np.float32)
                row = np.zeros(npk, np.float32)
                row[valid] = cur_bag[perm[valid]]
                rows.append(row)
            bags = jax.device_put(
                jnp.asarray(np.stack(rows)),
                NamedSharding(mesh, P(None, DATA_AXIS)))
        else:
            bags = jnp.ones((C, 1), jnp.float32)
        trees_st, scores, val_scores, val_hist = step(
            bins_d, scores, real_d, wmul_d, qidx_d, qmask_d, gains_d,
            labq_d, invmax_d, goss_keys_r[it:it + C], bags, fi_stack,
            val_bins_d, val_scores)
        chunks.append(trees_st)
        stop = False
        if has_val:
            vh = np.asarray(val_hist)[:, :nv]
            for j in range(C):
                margins = (_rf_margins(init, vh[j], it + j)
                           if use_rf_rk else vh[j])
                metric = float(val_metric(margins, val_labels_np,
                                          val_weights))
                gi = it + j
                if metric < best_metric - 1e-12:
                    best_metric, best_iter = metric, gi
                elif esr > 0 and gi - best_iter >= esr:
                    if params.verbosity > 0:
                        log.info("Early stopping at iteration %d "
                                 "(best %d, metric %.6f)", gi, best_iter,
                                 best_metric)
                    stop_iter = best_iter + 1
                    stop = True
                    break
        if stop:
            break
        it += C

    trees, nls = _fetch_host_trees(chunks, params.num_leaves, mapper)
    trees, nls = trees[:stop_iter], nls[:stop_iter]
    trees, stop_iter = _truncate_no_growth(trees, nls, 1, stop_iter,
                                           params.verbosity)
    if use_rf_rk:
        _rf_average_trees(trees, 1)
    return _finalize_booster(trees, 1, init, params, objective, mapper,
                             feature_names, f, stop_iter)


def _rf_margins(init, vh_row, tree_idx: int):
    """rf ensemble margins at iteration ``tree_idx``: trees are unshrunk
    raw fits, so the margin is init + running AVERAGE of the tree outputs
    (val_scores start at init, which must not be divided down)."""
    return init + (vh_row - init) / (tree_idx + 1)


def _rf_average_trees(trees, K: int) -> None:
    """Bake the 1/T random-forest averaging weight into the exported
    trees (the model output is the average of the raw trees)."""
    if not trees:
        return
    avg = 1.0 / (len(trees) // K)
    for t in trees:
        t.leaf_value = t.leaf_value * avg
        t.internal_value = t.internal_value * avg
        t.shrinkage = avg


def _feat_info_from_mapper(mapper: BinMapper, f: int) -> np.ndarray:
    """(f, 3) [mask, is_cat, n_value_bins] from the fitted BinMapper."""
    fi = np.zeros((f, 3), np.float32)
    fi[:, 0] = 1.0
    if mapper.has_categorical:
        fi[:, 1] = mapper.categorical.astype(np.float32)
        fi[:, 2] = [mapper.feature_num_bins(j) for j in range(f)]
    return fi


def _finalize_booster(trees, K, init, params, objective, mapper,
                      feature_names, f, stop_iter) -> Booster:
    if trees and params.boost_from_average and init != 0.0:
        # Bake the init score into the first tree per class so the exported
        # model is self-contained, as LightGBM does for boost_from_average.
        for k in range(K):
            t = trees[k]
            t.leaf_value = t.leaf_value + init
            t.internal_value = t.internal_value + init

    # pass_through keys that NAME TrainParams fields were applied by
    # __post_init__ and are already reflected in the typed values above
    # (num_iterations especially records the early-stopped count, which a
    # raw spread would clobber); only engine-unknown keys record verbatim
    extra = {k: v for k, v in params.pass_through.items()
             if not hasattr(params, k)}
    engine_params = {
        "boosting": params.boosting,
        "objective": objective.model_str,
        "num_iterations": str(stop_iter),
        "learning_rate": f"{params.learning_rate:g}",
        "num_leaves": str(params.num_leaves),
        "max_depth": str(params.max_depth),
        "max_bin": str(params.max_bin),
        **extra,
    }
    return Booster(
        trees, num_class=K, objective_str=objective.model_str,
        init_score=0.0, feature_names=feature_names,
        feature_infos=mapper.feature_infos(),
        max_feature_idx=f - 1, params=engine_params)


def _train_distributed_dart(bins, labels, w, mapper, objective, params,
                            cfg, mesh, feature_names, init, rng, bag_rng,
                            init_scores, val_bins=None, val_labels=None,
                            val_weights=None, val_metric=None,
                            callbacks=None, shard_data=None) -> Booster:
    """Dart boosting over the mesh (any layout: the feature-sharded
    score update walks trees via per-level psum).

    Dropout bookkeeping (which trees drop, per-tree scales) is host-side
    RNG over scalars — identical to the serial dart path, so a mesh run
    with the same dropSeed reproduces the serial ensemble structure.  Only
    the array work rides the mesh: the grow step (histogram psums inside)
    via :func:`make_dart_step` and the dropped-tree subtraction via
    :func:`make_tree_predict` on replicated trees over data-sharded rows.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..core.mesh import DATA_AXIS
    from .distributed import (make_dart_step, make_tree_predict,
                              prepare_arrays, prepare_arrays_from_shards)

    K = objective.num_model_per_iteration
    T = params.num_iterations
    use_bag = params.bagging_freq > 0 and params.bagging_fraction < 1.0
    use_ff = params.feature_fraction < 1.0
    if params.fault_tolerant_retries > 0:
        log.warning("faultTolerantRetries is inert for boostingType='dart'"
                    " (per-iteration host loop; no chunk snapshots)")
    if params.checkpoint_dir:
        log.warning("checkpoint_dir is inert for mesh dart (per-iteration"
                    " host loop; no chunk boundaries to snapshot)")

    if shard_data is not None:
        sizes = list(shard_data["sizes"])
        S_sh = max(sizes)
        n = sum(sizes)
        f = next(b.shape[1] for b in shard_data["bins_shards"]
                 if b is not None)
        real_pos = np.concatenate(
            [d * S_sh + np.arange(sz) for d, sz in enumerate(sizes)])
        n_padded = len(sizes) * S_sh
        bins_d, labels_d, w_d, real, scores, rp, fp =             prepare_arrays_from_shards(
                shard_data["bins_shards"], shard_data["label_shards"],
                shard_data["weight_shards"], mesh, K, init,
                mapper.bin_dtype,
                shard_rows=shard_data.get("shard_rows"),
                init_score_shards=shard_data.get("init_score_shards"))
    else:
        n, f = bins.shape
        bins_np = np.asarray(bins, mapper.bin_dtype)
        bins_d, labels_d, w_d, real, scores, rp, fp = prepare_arrays(
            bins_np, np.asarray(labels), np.asarray(w, np.float32), mesh,
            K, init, init_scores)
        real_pos = np.arange(n)
        n_padded = n + rp
    fi_base = np.zeros((f + fp, 3), np.float32)
    fi_base[:f] = _feat_info_from_mapper(mapper, f)
    L = params.num_leaves

    step = make_dart_step(mesh, objective, cfg, params.learning_rate,
                          num_class=K)
    pred = make_tree_predict(mesh, L, num_class=K)
    binsT_d = jnp.transpose(bins_d)   # fit-invariant, once per fit

    # dart rejects early stopping upstream (the dropped-tree rescaling is
    # not invertible by truncation), so a validation set has nothing to
    # decide here — val args are accepted for signature parity and ignored,
    # exactly like the serial dart path's inert metric would be.
    dart_rng = np.random.default_rng(params.drop_seed)
    bag_sh = NamedSharding(mesh, P(DATA_AXIS))

    def upload_bag(mask_n):
        # scatter the n-row mask into the padded global layout (pad rows
        # stay 0; under sharded ingestion real rows sit per-shard slice)
        padded = np.zeros(n_padded, np.float32)
        padded[real_pos] = mask_n
        return jax.device_put(jnp.asarray(padded), bag_sh)

    bag_state = {"dev": upload_bag(np.ones(n, np.float32))}

    def bag_draw(it):
        if use_bag and it % params.bagging_freq == 0:
            bag_state["dev"] = upload_bag(
                (bag_rng.random(n) < params.bagging_fraction
                 ).astype(np.float32))
        return bag_state["dev"]

    def fi_draw(_it):
        if use_ff:
            return jnp.asarray(_draw_feature_fraction(
                rng, fi_base, f, params.feature_fraction))
        return jnp.asarray(fi_base)

    def grow_unit(s_minus, bagm, fi):
        return step(bins_d, binsT_d, s_minus, labels_d, w_d, bagm, fi)

    units, trees_list, scales, scores = _dart_host_loop(
        T, K, dart_rng, params, scores, bag_draw, fi_draw, grow_unit,
        lambda u: pred(u, bins_d), callbacks)

    trees_chunks = []
    if trees_list:
        trees_chunks = [jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *trees_list)]
    trees, nls = _fetch_host_trees(trees_chunks, L, mapper)
    trees, stop_iter = _truncate_no_growth(trees, nls, K, T,
                                           params.verbosity)
    for t, s in zip(trees, np.repeat(scales, K)):
        t.leaf_value = t.leaf_value * s
        t.internal_value = t.internal_value * s
        t.shrinkage = s
    return _finalize_booster(trees, K, init, params, objective, mapper,
                             feature_names, f, stop_iter)


def _train_distributed(bins, labels, w, mapper, objective, params, cfg, mesh,
                       feature_names, init, rng, bag_rng,
                       init_scores=None, val_bins=None, val_labels=None,
                       val_weights=None, val_metric=None,
                       callbacks=None, shard_data=None,
                       val_init_scores=None) -> Booster:
    """Distributed boosting: the whole iteration loop is ONE shard_mapped
    ``lax.scan`` launch (no per-iteration host round-trips); with a
    validation set the loop chunks and the host replays per-iteration
    metrics for early stopping, exactly like the serial path.

    ``shard_data``: multi-host ingestion (SURVEY.md §7 hard part 4) — a
    dict of per-shard inputs (``bins_shards``/``label_shards``/
    ``weight_shards``/``sizes``/``init_score_shards``) that feed the mesh
    through ``prepare_arrays_from_shards`` so the global binned matrix is
    never materialized; ``bins`` is then ignored.  Bagging masks scatter
    to each shard's padded slice (per-machine bagging, as distributed
    LightGBM), and the fault-tolerance replay re-runs the same per-shard
    upload."""
    from .distributed import (make_boost_scan, make_goss_scan,
                              make_multiclass_scan, prepare_arrays,
                              prepare_arrays_from_shards)

    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..core.mesh import DATA_AXIS, FEATURE_AXIS, pad_to_multiple

    if shard_data is not None:
        sizes = list(shard_data["sizes"])
        S_sh = max(sizes)
        n = sum(sizes)
        f = next(b.shape[1] for b in shard_data["bins_shards"]
                 if b is not None)
        # positions of real rows inside the (D*S,) padded global layout
        real_pos = np.concatenate(
            [d * S_sh + np.arange(s) for d, s in enumerate(sizes)])
        n_padded = len(sizes) * S_sh
    else:
        n, f = bins.shape
    K = objective.num_model_per_iteration
    T = params.num_iterations
    esr = params.early_stopping_round
    use_bag = params.bagging_freq > 0 and params.bagging_fraction < 1.0
    use_ff = params.feature_fraction < 1.0
    use_goss_m = params.boosting == "goss"
    use_rf_m = params.boosting == "rf"
    has_val = val_bins is not None and val_metric is not None
    if use_goss_m:
        dn_pre = int(mesh.shape[DATA_AXIS])
        if shard_data is not None:
            # k1/k2 are SPMD trace constants shared by every shard; size
            # them from the MEAN real shard rows.  Pad rows carry zero
            # gradients, so an undersized shard degrades gracefully
            # toward training on all its rows (the tiny-shard fallback),
            # never toward corrupt contributions — but warn when the
            # layout is badly skewed.
            s_local = max(1, int(np.ceil(n / len(sizes))))
            if max(sizes) > 2 * min(sizes) and params.verbosity >= 0:
                log.warning(
                    "GOSS with sharded ingestion: shard sizes %s are "
                    "imbalanced; per-shard sample fractions will differ "
                    "(small shards train closer to full)", sizes)
        else:
            s_local = pad_to_multiple(n, dn_pre) // dn_pre
        k1 = max(1, int(np.ceil(s_local * params.top_rate)))
        k2 = max(1, int(np.ceil(s_local * params.other_rate)))
        if k1 + k2 >= s_local:
            use_goss_m = False   # tiny shards: nothing to shrink
            if params.verbosity > 0:
                log.info("GOSS sample covers every local row; mesh "
                         "training falls back to plain gbdt")
        else:
            goss_amp_m = (1.0 - params.top_rate) / params.other_rate
            goss_keys_m = jax.random.split(
                jax.random.PRNGKey(params.bagging_seed),
                params.num_iterations)
    # Mesh checkpointing (checkpoint_dir is LIVE here, serial-style):
    # the fingerprint is computed from the ORIGINAL inputs — before any
    # EFB rebundling rebinds ``bins`` — plus the mesh topology, so a
    # resume under a different (process count, shard layout) starts
    # fresh instead of scattering shards wrongly.
    ckpt = params.checkpoint_dir
    ckpt_fp = None
    ckpt_local = ""
    if ckpt:
        ckpt_fp = _ckpt_fingerprint_mesh(n, f, K, params, labels, bins,
                                         w, init_scores, mesh,
                                         shard_data)
        ckpt_local = _local_bins_digest(shard_data)

    # EFB under a data mesh: one bundling plan from the full host matrix
    # (columns are global), per-shard bundled rows, shard-local expansion
    # before the psum.  GOSS scores through the training matrix by
    # original feature id and a feature-sharded mesh would split bundles,
    # so both are excluded; voting's shard-local vote scan likewise.
    efb_dev_m, efb_host_m = None, None
    from .distributed import _feat_n as _feat_shards
    # per-tree collective accounting for the chunk monitor: evaluated on
    # the sharded cfg (axis names attach inside the scan builders)
    coll_sched_m = _collective_sched_for(cfg, mesh, n, f)
    if params.enable_bundle and not mapper.has_categorical \
            and mapper.num_total_bins <= 256 \
            and _feat_shards(mesh) == 1 \
            and cfg.voting_k == 0 and not use_goss_m \
            and shard_data is None:  # EFB plans need the full host matrix
        efb_dev_m, efb_host_m, bundled = _build_efb(
            bins, mapper, params, f, verbosity_tag=" (mesh)")
        if efb_dev_m is not None:
            bins = bundled

    def build_step(efb_arg):
        """(Re)build the shard_mapped chunk program — the fault-tolerance
        replay needs fresh EFB closure constants after a device loss."""
        if use_goss_m:
            return make_goss_scan(
                mesh, objective, cfg, params.learning_rate, k1, k2,
                goss_amp_m, has_val, num_class=K)
        if K > 1:
            return make_multiclass_scan(
                mesh, objective, cfg, params.learning_rate, K, use_bag,
                has_val, efb=efb_arg, rf=use_rf_m)
        return make_boost_scan(
            mesh, objective, cfg, params.learning_rate, use_bag, has_val,
            rf=use_rf_m, efb=efb_arg)

    step = build_step(efb_dev_m)
    if shard_data is not None:
        def prep_arrays():
            return prepare_arrays_from_shards(
                shard_data["bins_shards"], shard_data["label_shards"],
                shard_data["weight_shards"], mesh, K, init,
                mapper.bin_dtype,
                shard_rows=shard_data.get("shard_rows"),
                init_score_shards=shard_data.get("init_score_shards"))
    else:
        bins_np = np.asarray(bins, mapper.bin_dtype)
        labels_np = np.asarray(labels)
        w_np = np.asarray(w, np.float32)

        def prep_arrays():
            return prepare_arrays(bins_np, labels_np, w_np, mesh, K, init,
                                  init_scores)
    bins_d, labels_d, w_d, real, scores, rp, fp = prep_arrays()
    if shard_data is None:
        real_pos = np.arange(n)
        n_padded = n + rp
    f_padded = f + fp

    # feat_info stays per ORIGINAL feature under EFB (histograms expand
    # back to f features before split finding); fp then pads bundle
    # columns, not features
    fi_base = np.zeros((f if efb_dev_m is not None else f_padded, 3),
                       np.float32)
    fi_base[:f] = _feat_info_from_mapper(mapper, f)

    dn = int(mesh.shape[DATA_AXIS])
    if has_val:
        nv = val_bins.shape[0]
        vrp = pad_to_multiple(nv, dn) - nv
        vb = np.asarray(val_bins, mapper.bin_dtype)
        if vrp:
            vb = np.concatenate(
                [vb, np.zeros((vrp, f), vb.dtype)], axis=0)
        # all features per shard (trees are replicated; each data shard
        # scores its own validation slice)
        val_bins_d = jax.device_put(
            jnp.asarray(vb), NamedSharding(mesh, P(DATA_AXIS, None)))
        vshape = (nv + vrp, K) if K > 1 else (nv + vrp,)
        vspec = P(DATA_AXIS, None) if K > 1 else P(DATA_AXIS)
        vs0 = np.full(vshape, init, np.float32)
        if val_init_scores is not None:
            vsc = np.asarray(val_init_scores, np.float32)
            vsc = vsc if vs0.ndim == vsc.ndim else vsc[:, None]
            vs0[:nv] = vs0[:nv] + vsc
        val_scores = jax.device_put(
            jnp.asarray(vs0), NamedSharding(mesh, vspec))
        val_labels_np = np.asarray(val_labels)
    else:
        val_bins_d = jax.device_put(
            jnp.zeros((dn, f_padded), mapper.bin_dtype),
            NamedSharding(mesh, P(DATA_AXIS, None)))
        val_scores = jax.device_put(
            jnp.zeros((dn, K) if K > 1 else (dn,), jnp.float32),
            NamedSharding(mesh, P(DATA_AXIS, None) if K > 1
                          else P(DATA_AXIS)))

    def iter_fi_dist(_gi):
        if not use_ff:
            return fi_base
        return _draw_feature_fraction(rng, fi_base, f,
                                      params.feature_fraction)

    # Chunk when bagging materializes per-iteration (chunk, n) masks or a
    # validation set stacks per-iteration (chunk, n_val) margins;
    # otherwise the whole fit is one launch with a constant (T, 1) mask
    # (pad rows ride the (n,) `real` mask inside the step).
    chunk = T
    if use_bag:
        chunk = min(chunk, 64)
    if has_val:
        chunk = min(chunk, max(min(esr, 64), 8) if esr > 0 else 64)
    if callbacks:
        # callbacks are a per-iteration host contract: bound the chunk so
        # the host syncs often enough to replay them in order
        chunk = min(chunk, 8)
    ftr = params.fault_tolerant_retries
    if ftr > 0:
        # the mesh gang-restart analog (SURVEY.md §5.3): bounded chunks
        # bound the replay; the replay re-runs prep_arrays(), which closes
        # over the host inputs (monolithic arrays or per-host shards), so
        # a failure that kills every device buffer in the gang re-uploads
        # from the same source — no second host copy.
        chunk = min(chunk, 32)
        ft_vb = vb if has_val else None   # already padded
    if ckpt:
        # bounded chunks = bounded lost work after a controller death
        chunk = min(chunk, max(1, params.checkpoint_chunk))
    cur = np.ones(n, np.float32)
    chunks: List[TreeArrays] = []
    cb_list: List[TreeArrays] = []
    best_metric, best_iter = np.inf, -1
    stop_iter = T
    it = 0
    if ckpt:
        snap = _ckpt_load_mesh(ckpt, ckpt_fp, scores, val_scores,
                               local_digest=ckpt_local)
        if jax.process_count() > 1:
            # the verdict must be UNANIMOUS: the local_digest check (and
            # a torn own-state read) can diverge per process, and a gang
            # where one controller resumes while another starts fresh
            # computes garbage collectives
            from jax.experimental import multihost_utils
            peers_ok = multihost_utils.process_allgather(
                np.asarray([snap is not None], np.int32))
            if snap is not None and not bool(peers_ok.all()):
                log.warning("a peer controller rejected the mesh "
                            "checkpoint; starting fresh gang-wide")
                train_stats.incr("ckpt_discarded")
                _ckpt_event("ckpt_discarded", reason="peer_rejected",
                            mesh=True)
                snap = None
        if snap is None:
            # purge stale generations: write-once chunk files of an
            # abandoned fit must not be skipped-over by this run's
            # saves and then stitched into ITS meta (the verdict is
            # gang-unanimous — see above — so only process 0 deletes,
            # and the barrier keeps peers from racing their first save
            # against the purge)
            if jax.process_index() == 0:
                _ckpt_clear(ckpt)
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("ckpt_stale_clear")
        else:
            train_stats.incr("ckpt_resumed")
            _ckpt_event("ckpt_resumed", it=int(snap["it"]), mesh=True)
            it = snap["it"]
            chunks = list(snap["trees_chunks"])
            scores = snap["scores"]
            val_scores = snap["val_scores"]
            cur = np.asarray(snap["cur_bag"], np.float32)
            rng.bit_generator.state = snap["rng_state"]
            bag_rng.bit_generator.state = snap["bag_rng_state"]
            best_metric = snap["best_metric"]
            best_iter = snap["best_iter"]
            if callbacks:
                log.warning("resuming mesh fit from checkpoint at "
                            "iteration %d: callbacks replay only for "
                            "the remaining iterations", it)
            elif params.verbosity > 0:
                log.info("resuming mesh fit from checkpoint at "
                         "iteration %d", it)
    while it < T:
        C = min(chunk, T - it)
        if use_bag:
            rows = []
            for j in range(C):
                if (it + j) % params.bagging_freq == 0:
                    # draw exactly n randoms so the stream matches a
                    # serial run with the same baggingSeed, then scatter
                    # into the padded layout (pad rows stay 0; under
                    # sharded ingestion real rows sit per-shard slice)
                    cur = (bag_rng.random(n) < params.bagging_fraction
                           ).astype(np.float32)
                row = np.zeros(n_padded, np.float32)
                row[real_pos] = cur
                rows.append(row)
            bags_host = np.stack(rows)
            bags = jax.device_put(jnp.asarray(bags_host),
                                  NamedSharding(mesh, P(None, DATA_AXIS)))
        else:
            bags_host = np.ones((C, 1), np.float32)
            bags = jnp.ones((C, 1), jnp.float32)
        if use_ff:
            fi_host = np.stack([iter_fi_dist(it + j) for j in range(C)])
        else:
            fi_host = np.broadcast_to(fi_base, (C,) + fi_base.shape)
        fi_stack = jnp.asarray(fi_host)
        def run_step(scores_in, val_scores_in):
            if use_goss_m:
                return step(
                    bins_d, scores_in, labels_d, w_d, real,
                    goss_keys_m[it:it + C], fi_stack, val_bins_d,
                    val_scores_in)
            return step(
                bins_d, scores_in, labels_d, w_d, real, bags, fi_stack,
                val_bins_d, val_scores_in)

        t_chunk = time.perf_counter()
        if ftr > 0:
            # one D2H snapshot per chunk buys replay; the happy path
            # reuses the LIVE device buffers (donation is safe — the
            # snapshot covers the replay)
            snap = (np.asarray(scores), np.asarray(val_scores))
            for attempt in range(ftr + 1):
                try:
                    if attempt == 0:
                        s_in, v_in = scores, val_scores
                    else:
                        s_in = jax.device_put(jnp.asarray(snap[0]),
                                              scores.sharding)
                        v_in = jax.device_put(jnp.asarray(snap[1]),
                                              val_scores.sharding)
                    trees_st, scores, val_scores, val_hist = run_step(
                        s_in, v_in)
                    jax.block_until_ready(trees_st)
                    break
                except Exception as e:  # noqa: BLE001 - device loss
                    from jax.experimental import checkify as _ck
                    if isinstance(e, _ck.JaxRuntimeError):
                        raise   # deterministic data bug: replay would
                        # fail identically
                    if attempt >= ftr:
                        raise
                    train_stats.incr("chunks_replayed")
                    _ckpt_event("chunk_replayed", it=int(it),
                                attempt=attempt + 1, mesh=True)
                    log.warning(
                        "mesh chunk at iteration %d failed (attempt "
                        "%d/%d); re-uploading the gang's inputs and "
                        "replaying", it, attempt + 1, ftr)
                    bins_d, labels_d, w_d, real, scores, _, _ = \
                        prep_arrays()
                    if use_goss_m:
                        # the PRNG key stack is a device buffer too
                        goss_keys_m = jax.random.split(
                            jax.random.PRNGKey(params.bagging_seed),
                            params.num_iterations)
                    if efb_dev_m is not None:
                        # the EFB maps are closure constants of the
                        # compiled step — dead with the gang; re-upload
                        # and rebuild the program around them
                        efb_dev_m = _efb_dev_from_host(efb_host_m)
                        step = build_step(efb_dev_m)
                    if has_val:
                        val_bins_d = jax.device_put(
                            jnp.asarray(ft_vb),
                            NamedSharding(mesh, P(DATA_AXIS, None)))
                        val_scores = jax.device_put(
                            jnp.asarray(snap[1]),
                            NamedSharding(mesh, vspec))
                    else:
                        val_bins_d = jax.device_put(
                            jnp.zeros((dn, f_padded), mapper.bin_dtype),
                            NamedSharding(mesh, P(DATA_AXIS, None)))
                        val_scores = jax.device_put(
                            jnp.asarray(snap[1]),
                            NamedSharding(mesh, P(DATA_AXIS, None)
                                          if K > 1 else P(DATA_AXIS)))
                    if use_bag:
                        bags = jax.device_put(
                            jnp.asarray(bags_host),
                            NamedSharding(mesh, P(None, DATA_AXIS)))
                    else:
                        bags = jnp.asarray(bags_host)
                    fi_stack = jnp.asarray(fi_host)
        else:
            _p = get_profiler()
            _seq0 = _p.compile_seq()
            trees_st, scores, val_scores, val_hist = run_step(
                scores, val_scores)
            _t_host = time.perf_counter()
            jax.block_until_ready(trees_st)
            _t_done = time.perf_counter()
            _p.dispatch("train.boost_chunk", _t_host - t_chunk,
                        _t_done - _t_host, _p.compile_seq() - _seq0)
            _p.span("train.boost_chunk", _t_done - t_chunk,
                    journal=True, it=int(it), mesh=True,
                    host_ms=round((_t_host - t_chunk) * 1e3, 3),
                    device_ms=round((_t_done - _t_host) * 1e3, 3))
        chunks.append(trees_st)
        # objective=None: the gang's score vector is sharded (not fully
        # addressable on any one controller), so train loss is skipped
        # rather than gathered
        _monitor_chunk(it, it + C, time.perf_counter() - t_chunk, n, K,
                       cfg.hist_method, collective=cfg.collective,
                       coll_sched=coll_sched_m)
        stop = False
        if has_val:
            vh = np.asarray(val_hist)[:, :nv]    # drop val pad rows
            for j in range(C):
                margins = (_rf_margins(init, vh[j], it + j)
                           if use_rf_m else vh[j])
                metric = float(val_metric(margins, val_labels_np,
                                          val_weights))
                gi = it + j
                if metric < best_metric - 1e-12:
                    best_metric, best_iter = metric, gi
                elif esr > 0 and gi - best_iter >= esr:
                    if params.verbosity > 0:
                        log.info("Early stopping at iteration %d "
                                 "(best %d, metric %.6f)", gi, best_iter,
                                 best_metric)
                    stop_iter = best_iter + 1
                    stop = True
                    break
        if callbacks:
            # per-iteration host replay, same contract as the serial path:
            # cb(global_iter, flat list of per-iteration/per-class trees)
            upto = stop_iter if stop else it + C
            for j in range(upto - it):
                for kk in range(K):
                    cb_list.append(jax.tree_util.tree_map(
                        lambda a, j=j, kk=kk: a[j * K + kk], trees_st))
                for cb in callbacks:
                    cb(it + j, cb_list)
        if stop:
            break
        it += C
        if ckpt and it < T:
            # skip the final boundary: it == T would pay the D2H shard
            # copies and two gang barriers for a snapshot the clear
            # below deletes immediately
            _ckpt_save_mesh(ckpt, ckpt_fp, it, chunks, scores,
                            val_scores, cur, rng, bag_rng, best_metric,
                            best_iter, local_digest=ckpt_local)
    if ckpt:
        if jax.process_count() > 1:
            # every controller must be past its last possible read of
            # the snapshot before anyone deletes it
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("ckpt_clear")
        if jax.process_index() == 0:
            _ckpt_clear(ckpt)

    trees, nls = _fetch_host_trees(chunks, params.num_leaves, mapper)
    trees, nls = trees[:stop_iter * K], nls[:stop_iter * K]
    trees, stop_iter = _truncate_no_growth(trees, nls, K, stop_iter,
                                           params.verbosity)
    if use_rf_m:
        _rf_average_trees(trees, K)
    return _finalize_booster(trees, K, init, params, objective, mapper,
                             feature_names, f, stop_iter)
