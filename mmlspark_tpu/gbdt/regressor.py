"""LightGBMRegressor / LightGBMRegressionModel.

TPU-native re-implementation of lightgbm/LightGBMRegressor.scala (expected
path, UNVERIFIED; SURVEY.md §2.1).  Supports the reference's regression
objectives: l2, l1, huber, fair, poisson, quantile, mape.
"""

from __future__ import annotations

import numpy as np

from ..core.params import Param, TypeConverters
from ..core.schema import DataTable, features_matrix
from .base import LightGBMBase, LightGBMModelBase
from .booster import Booster


class LightGBMRegressor(LightGBMBase):
    _default_objective = "regression"

    alpha = Param("alpha", "Alpha for huber/quantile objectives", default=0.9,
                  typeConverter=TypeConverters.toFloat)
    fairC = Param("fairC", "C for fair objective", default=1.0,
                  typeConverter=TypeConverters.toFloat)
    poissonMaxDeltaStep = Param("poissonMaxDeltaStep",
                                "Safety for poisson optimization",
                                default=0.7,
                                typeConverter=TypeConverters.toFloat)
    tweedieVariancePower = Param("tweedieVariancePower",
                                 "Tweedie variance power", default=1.5,
                                 typeConverter=TypeConverters.toFloat)

    def _objective_kwargs(self):
        return dict(alpha=self.getAlpha(), fair_c=self.getFairC(),
                    poisson_max_delta_step=self.getPoissonMaxDeltaStep(),
                    tweedie_variance_power=self.getTweedieVariancePower())

    def _val_metric(self):
        def l2(scores, labels, weights):
            d = (scores - labels) ** 2
            if weights is not None:
                return float(np.average(d, weights=weights))
            return float(np.mean(d))
        return l2

    def _make_model(self, booster: Booster) -> "LightGBMRegressionModel":
        return LightGBMRegressionModel(booster=booster)


class LightGBMRegressionModel(LightGBMModelBase):

    def _transform(self, table: DataTable) -> DataTable:
        X = features_matrix(table, self.getFeaturesCol())
        pred = np.asarray(self._booster.predict(X))
        out = self._with_shap(table, X)
        return out.withColumn(self.getPredictionCol(),
                              pred.astype(np.float64))
