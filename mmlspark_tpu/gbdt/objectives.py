"""Training objectives: gradient/hessian functions.

TPU-native analogs of LightGBM's ``ObjectiveFunction`` subclasses, which the
reference selects via its ``objective`` param and passes to the native engine
(SURVEY.md §2.1 LightGBM params; §3.1 hot loop computes grad/hess natively).
Each objective is a pure jax function ``(scores, labels, weights) → (g, h)``
so it fuses into the jitted training step.

Semantics track LightGBM:

* ``binary``: logistic loss with ``sigmoid`` scaling and optional
  ``is_unbalance``/``scale_pos_weight`` label weighting;
  ``boost_from_average`` init score = log(p/(1-p))/sigmoid.
* ``regression`` (l2), ``regression_l1`` (gradient = sign, hessian = 1),
  ``huber``, ``fair``, ``poisson``, ``quantile``, ``mape``.
* ``multiclass``: one-vs-all softmax, K trees per iteration,
  hessian = 2·p·(1-p) · factor (K/(K-1)) as in LightGBM.
* ``lambdarank``: in :mod:`mmlspark_tpu.gbdt.ranking` (pairwise ΔNDCG).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray
GradFn = Callable[[Array, Array, Array], Tuple[Array, Array]]


def sigmoid(x):
    return jax.nn.sigmoid(x)


class Objective:
    """Base: subclasses define grad/hess and the boost-from-average init.

    Objectives are passed to jitted boost steps as *static* arguments, so
    they hash by value (type + full instance state, including what
    ``prepare`` resolved): two fits with identical objective config hit the
    same XLA executable instead of recompiling per estimator instance.
    """

    name = "base"
    num_model_per_iteration = 1
    #: substring written into the LightGBM model file objective line
    model_str = "custom"

    def _key(self):
        return (type(self), tuple(sorted(self.__dict__.items())))

    def __eq__(self, other):
        return isinstance(other, Objective) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def prepare(self, labels: np.ndarray, weights: np.ndarray) -> None:
        """Resolve label statistics (class weights etc.); always called once
        before training, independent of boost_from_average."""

    def init_score(self, labels: np.ndarray, weights: np.ndarray) -> float:
        return 0.0

    def grad_hess(self, scores: Array, labels: Array,
                  weights: Array) -> Tuple[Array, Array]:
        raise NotImplementedError

    def transform_prediction(self, scores: Array) -> Array:
        """Raw margin → output space (e.g. sigmoid for binary)."""
        return scores

    def train_loss(self, scores: np.ndarray, labels: np.ndarray,
                   weights: Optional[np.ndarray] = None
                   ) -> Optional[float]:
        """Cheap host-side training loss for live telemetry (the
        ``train_loss`` gauge / ``boost_chunk`` journal field) —
        objectives without a closed form return ``None`` and the
        monitor skips the gauge.  Pure numpy on HOST copies: called at
        chunk boundaries, never inside the jitted step."""
        return None


class BinaryObjective(Objective):
    name = "binary"
    model_str = "binary sigmoid:1"

    def __init__(self, sigmoid_coef: float = 1.0, is_unbalance: bool = False,
                 scale_pos_weight: float = 1.0):
        self.sigma = float(sigmoid_coef)
        self.is_unbalance = is_unbalance
        self.scale_pos_weight = float(scale_pos_weight)
        self.model_str = f"binary sigmoid:{self.sigma:g}"
        self._pos_w = 1.0  # resolved by prepare() from label stats
        self._neg_w = 1.0

    def prepare(self, labels, weights):
        pos = float(np.sum(weights * (labels > 0)))
        neg = float(np.sum(weights)) - pos
        if self.is_unbalance and pos > 0 and neg > 0:
            # up-weight whichever class is rarer, as LightGBM does
            if pos < neg:
                self._pos_w = neg / pos
            else:
                self._neg_w = pos / neg
        elif self.scale_pos_weight != 1.0:
            self._pos_w = self.scale_pos_weight

    def init_score(self, labels, weights):
        pos = float(np.sum(weights * (labels > 0)))
        neg = float(np.sum(weights)) - pos
        if pos <= 0 or neg <= 0:
            return 0.0
        p = pos / (pos + neg)
        return float(np.log(p / (1.0 - p)) / self.sigma)

    def grad_hess(self, scores, labels, weights):
        p = sigmoid(self.sigma * scores)
        w = weights * jnp.where(labels > 0, self._pos_w, self._neg_w)
        g = self.sigma * (p - labels) * w
        h = self.sigma * self.sigma * p * (1.0 - p) * w
        return g, h

    def transform_prediction(self, scores):
        return sigmoid(self.sigma * scores)

    def train_loss(self, scores, labels, weights=None):
        """Weighted logloss (numpy, clipped for stability)."""
        y = (np.asarray(labels) > 0).astype(np.float64)
        p = 1.0 / (1.0 + np.exp(-self.sigma * np.asarray(
            scores, np.float64)))
        p = np.clip(p, 1e-12, 1.0 - 1e-12)
        ll = -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
        w = (np.ones_like(ll) if weights is None
             else np.asarray(weights, np.float64))
        s = float(w.sum())
        return float((ll * w).sum() / s) if s > 0 else None


class RegressionL2(Objective):
    name = "regression"
    model_str = "regression"

    def init_score(self, labels, weights):
        s = float(np.sum(weights))
        return float(np.sum(weights * labels) / s) if s > 0 else 0.0

    def grad_hess(self, scores, labels, weights):
        return (scores - labels) * weights, weights

    def train_loss(self, scores, labels, weights=None):
        """Weighted mean squared error (numpy)."""
        err = (np.asarray(scores, np.float64)
               - np.asarray(labels, np.float64)) ** 2
        w = (np.ones_like(err) if weights is None
             else np.asarray(weights, np.float64))
        s = float(w.sum())
        return float((err * w).sum() / s) if s > 0 else None


class RegressionL1(Objective):
    name = "regression_l1"
    model_str = "regression_l1"

    def init_score(self, labels, weights):
        return float(np.median(labels))

    def grad_hess(self, scores, labels, weights):
        g = jnp.sign(scores - labels) * weights
        return g, weights


class HuberObjective(Objective):
    name = "huber"
    model_str = "huber"

    def __init__(self, alpha: float = 0.9):
        self.alpha = float(alpha)

    def init_score(self, labels, weights):
        s = float(np.sum(weights))
        return float(np.sum(weights * labels) / s) if s > 0 else 0.0

    def grad_hess(self, scores, labels, weights):
        d = scores - labels
        g = jnp.where(jnp.abs(d) <= self.alpha, d,
                      self.alpha * jnp.sign(d)) * weights
        return g, weights


class FairObjective(Objective):
    name = "fair"
    model_str = "fair"

    def __init__(self, c: float = 1.0):
        self.c = float(c)

    def init_score(self, labels, weights):
        return 0.0

    def grad_hess(self, scores, labels, weights):
        d = scores - labels
        g = self.c * d / (jnp.abs(d) + self.c) * weights
        h = self.c * self.c / jnp.square(jnp.abs(d) + self.c) * weights
        return g, h


class PoissonObjective(Objective):
    name = "poisson"
    model_str = "poisson"

    def __init__(self, max_delta_step: float = 0.7):
        self.max_delta_step = float(max_delta_step)

    def init_score(self, labels, weights):
        s = float(np.sum(weights))
        mean = float(np.sum(weights * labels) / s) if s > 0 else 1.0
        return float(np.log(max(mean, 1e-12)))

    def grad_hess(self, scores, labels, weights):
        mu = jnp.exp(scores)
        g = (mu - labels) * weights
        h = mu * jnp.exp(self.max_delta_step) * weights
        return g, h

    def transform_prediction(self, scores):
        return jnp.exp(scores)


class QuantileObjective(Objective):
    name = "quantile"
    model_str = "quantile"

    def __init__(self, alpha: float = 0.9):
        self.alpha = float(alpha)

    def init_score(self, labels, weights):
        return float(np.quantile(labels, self.alpha))

    def grad_hess(self, scores, labels, weights):
        d = scores - labels
        g = jnp.where(d >= 0, 1.0 - self.alpha, -self.alpha) * weights
        return g, weights


class MapeObjective(Objective):
    name = "mape"
    model_str = "mape"

    def init_score(self, labels, weights):
        return float(np.median(labels))

    def grad_hess(self, scores, labels, weights):
        denom = jnp.maximum(jnp.abs(labels), 1.0)
        g = jnp.sign(scores - labels) / denom * weights
        h = weights / denom
        return g, h


class GammaObjective(Objective):
    """Gamma deviance with log link (LightGBM objective=gamma;
    src/objective/regression_objective.hpp RegressionGammaLoss, expected
    path, UNVERIFIED): g = 1 - y·e^{-s}, h = y·e^{-s}."""

    name = "gamma"
    model_str = "gamma"

    def init_score(self, labels, weights):
        s = float(np.sum(weights))
        mean = float(np.sum(weights * labels) / s) if s > 0 else 1.0
        return float(np.log(max(mean, 1e-12)))

    def grad_hess(self, scores, labels, weights):
        ey = labels * jnp.exp(-scores)
        g = (1.0 - ey) * weights
        h = ey * weights
        return g, h

    def transform_prediction(self, scores):
        return jnp.exp(scores)


class TweedieObjective(Objective):
    """Tweedie deviance, log link, variance power ρ ∈ (1, 2) (LightGBM
    objective=tweedie, tweedie_variance_power; RegressionTweedieLoss,
    expected path, UNVERIFIED):
    g = -y·e^{(1-ρ)s} + e^{(2-ρ)s}, h the score derivative of g."""

    name = "tweedie"

    def __init__(self, rho: float = 1.5):
        if not 1.0 < rho < 2.0:
            raise ValueError("tweedie_variance_power must be in (1, 2), "
                             f"got {rho}")
        self.rho = float(rho)
        self.model_str = "tweedie"

    def init_score(self, labels, weights):
        s = float(np.sum(weights))
        mean = float(np.sum(weights * labels) / s) if s > 0 else 1.0
        return float(np.log(max(mean, 1e-12)))

    def grad_hess(self, scores, labels, weights):
        a = jnp.exp((1.0 - self.rho) * scores)
        b = jnp.exp((2.0 - self.rho) * scores)
        g = (-labels * a + b) * weights
        h = (-labels * (1.0 - self.rho) * a
             + (2.0 - self.rho) * b) * weights
        return g, h

    def transform_prediction(self, scores):
        return jnp.exp(scores)


class CrossEntropyObjective(Objective):
    """Cross-entropy on PROBABILITY labels in [0, 1] (LightGBM
    objective=cross_entropy / xentropy): the binary gradient g = σ(s) - y
    without requiring hard 0/1 labels."""

    name = "cross_entropy"
    model_str = "cross_entropy"

    def init_score(self, labels, weights):
        s = float(np.sum(weights))
        p = float(np.sum(weights * labels) / s) if s > 0 else 0.5
        p = min(max(p, 1e-12), 1.0 - 1e-12)
        return float(np.log(p / (1.0 - p)))

    def grad_hess(self, scores, labels, weights):
        p = jax.nn.sigmoid(scores)
        g = (p - labels) * weights
        h = jnp.maximum(p * (1.0 - p), 1e-16) * weights
        return g, h

    def transform_prediction(self, scores):
        return jax.nn.sigmoid(scores)


class MulticlassOvaObjective(Objective):
    """One-vs-all multiclass (LightGBM objective=multiclassova): K
    INDEPENDENT sigmoid classifiers, one tree per class per iteration;
    prediction = per-class sigmoids normalized to sum 1 (LightGBM's
    OVA converter)."""

    name = "multiclassova"

    def __init__(self, num_class: int, sigmoid_coef: float = 1.0):
        if num_class < 2:
            raise ValueError("multiclassova requires num_class >= 2")
        self.num_class = int(num_class)
        self.num_model_per_iteration = self.num_class
        self.sigma = float(sigmoid_coef)
        self.model_str = (f"multiclassova num_class:{self.num_class} "
                          f"sigmoid:{self.sigma:g}")

    def init_score(self, labels, weights):
        return 0.0

    def grad_hess(self, scores, labels, weights):
        y = jax.nn.one_hot(labels.astype(jnp.int32), self.num_class,
                           dtype=scores.dtype)
        p = jax.nn.sigmoid(self.sigma * scores)
        w = weights[:, None]
        g = self.sigma * (p - y) * w
        h = self.sigma * self.sigma * p * (1.0 - p) * w
        return g, h

    def transform_prediction(self, scores):
        p = jax.nn.sigmoid(self.sigma * scores)
        return p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-12)


class MulticlassObjective(Objective):
    """Softmax over K per-class score columns; K trees per iteration."""

    name = "multiclass"

    def __init__(self, num_class: int):
        if num_class < 2:
            raise ValueError("multiclass requires num_class >= 2")
        self.num_class = int(num_class)
        self.num_model_per_iteration = self.num_class
        self.model_str = f"multiclass num_class:{self.num_class}"
        self.factor = self.num_class / (self.num_class - 1.0)

    def init_score(self, labels, weights):
        return 0.0

    def grad_hess(self, scores, labels, weights):
        """scores: (n, K); labels: (n,) int class ids → (n, K) g/h."""
        p = jax.nn.softmax(scores, axis=-1)
        y = jax.nn.one_hot(labels.astype(jnp.int32), self.num_class,
                           dtype=p.dtype)
        w = weights[:, None]
        g = (p - y) * w
        h = self.factor * p * (1.0 - p) * w
        return g, h

    def transform_prediction(self, scores):
        return jax.nn.softmax(scores, axis=-1)

    def train_loss(self, scores, labels, weights=None):
        """Weighted softmax cross-entropy (numpy, log-sum-exp)."""
        s = np.asarray(scores, np.float64)
        s = s - s.max(axis=-1, keepdims=True)
        logp = s - np.log(np.exp(s).sum(axis=-1, keepdims=True))
        y = np.asarray(labels).astype(np.int64)
        nll = -logp[np.arange(len(y)), y]
        w = (np.ones_like(nll) if weights is None
             else np.asarray(weights, np.float64))
        tot = float(w.sum())
        return float((nll * w).sum() / tot) if tot > 0 else None


class _LambdarankStub(Objective):
    """Metadata-only objective: the ranker supplies grad/hess via its
    query-structured override (gbdt/ranking.py); init score is 0."""

    name = "lambdarank"
    model_str = "lambdarank"

    def grad_hess(self, scores, labels, weights):
        raise ValueError(
            "objective='lambdarank' needs query structure; use "
            "LightGBMRanker (with groupCol) instead of "
            "LightGBMClassifier/Regressor")


def _lambdarank_stub() -> Objective:
    return _LambdarankStub()


def get_objective(name: str, num_class: int = 1, **kwargs) -> Objective:
    name = name.lower()
    aliases = {
        "binary": lambda: BinaryObjective(
            sigmoid_coef=kwargs.get("sigmoid", 1.0),
            is_unbalance=kwargs.get("is_unbalance", False),
            scale_pos_weight=kwargs.get("scale_pos_weight", 1.0)),
        "regression": RegressionL2, "regression_l2": RegressionL2,
        "l2": RegressionL2, "mean_squared_error": RegressionL2,
        "mse": RegressionL2,
        "regression_l1": RegressionL1, "l1": RegressionL1,
        "mae": RegressionL1,
        "huber": lambda: HuberObjective(alpha=kwargs.get("alpha", 0.9)),
        "fair": lambda: FairObjective(c=kwargs.get("fair_c", 1.0)),
        "poisson": lambda: PoissonObjective(
            max_delta_step=kwargs.get("poisson_max_delta_step", 0.7)),
        "quantile": lambda: QuantileObjective(alpha=kwargs.get("alpha", 0.9)),
        "mape": MapeObjective,
        "gamma": GammaObjective,
        "tweedie": lambda: TweedieObjective(
            rho=kwargs.get("tweedie_variance_power", 1.5)),
        "cross_entropy": CrossEntropyObjective,
        "xentropy": CrossEntropyObjective,
        "multiclass": lambda: MulticlassObjective(num_class),
        "softmax": lambda: MulticlassObjective(num_class),
        "multiclassova": lambda: MulticlassOvaObjective(
            num_class, sigmoid_coef=kwargs.get("sigmoid", 1.0)),
        "ova": lambda: MulticlassOvaObjective(
            num_class, sigmoid_coef=kwargs.get("sigmoid", 1.0)),
        "lambdarank": _lambdarank_stub,
    }
    if name not in aliases:
        raise ValueError(f"Unknown objective {name!r}; "
                         f"supported: {sorted(aliases)}")
    return aliases[name]()
