"""Booster: the trained GBDT model container.

TPU-native analog of the reference's ``LightGBMBooster`` (serializable model
wrapper + predict; lightgbm/LightGBMBooster.scala, expected path, UNVERIFIED).
The reference wraps a native handle and round-trips models as LightGBM's
*text* format — an interop contract (SURVEY.md §5.4) this class preserves:
``save_native_model``/``load_native_model`` emit/parse LightGBM v3 model
files, so models exported here load in stock LightGBM and vice versa
(numerical splits; categorical splits are round 2).

Prediction runs as a single jitted scan over stacked tree arrays: rows
traverse all trees in parallel with gather-based walks (n·T·depth gathers),
instead of the reference's per-row JNI ``LGBM_BoosterPredictForMat`` calls —
its known scoring sore point (SURVEY.md §3.2).
"""

from __future__ import annotations

import functools
import hashlib
import io
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .grower import TreeArrays
from .binning import BinMapper

#: content-digest header (ISSUE 14 satellite): ``save_native_model``
#: prepends ONE comment line ``# mmlspark_tpu.digest.sha256=<hex>``
#: hashing everything after it, so model-file corruption (torn write,
#: bit rot) is detected at load EVERYWHERE — the registry, the fleet's
#: spawn-mode model handoff, a bare ``load_native_model`` — not only
#: where a registry manifest happens to carry a second digest.
#: Digest-less files (stock LightGBM exports, pre-ISSUE-14 saves) load
#: unchanged; the model-string API stays byte-identical to the
#: reference's text format for interop.
DIGEST_HEADER = "# mmlspark_tpu.digest.sha256="


class ModelDigestError(ValueError):
    """A native-model file's content no longer hashes to its embedded
    digest header — refuse to build a Booster from corrupt bytes."""


def with_digest_header(text: str) -> str:
    """Prepend the digest header line (idempotent: an already-stamped
    text is re-verified and returned unchanged)."""
    if text.startswith(DIGEST_HEADER):
        split_native_digest(text)     # re-verify, raises on mismatch
        return text
    h = hashlib.sha256(text.encode("utf-8")).hexdigest()
    return f"{DIGEST_HEADER}{h}\n{text}"


def split_native_digest(text: str) -> str:
    """Strip and VERIFY the digest header when present; return the
    bare model text.  Digest-less input passes through untouched
    (backward compatibility with stock LightGBM files)."""
    if not text.startswith(DIGEST_HEADER):
        # a bit-flipped HEADER must not demote the file to "digest-less"
        # and load unverified: any first line still recognisable as a
        # digest stamp but not byte-exact is corruption
        if ".digest.sha256=" in text[:len(DIGEST_HEADER) + 16]:
            raise ModelDigestError(
                "native model digest header is mangled (bit-flipped "
                "header line); refusing to load")
        return text
    line, _, body = text.partition("\n")
    want = line[len(DIGEST_HEADER):].strip()
    got = hashlib.sha256(body.encode("utf-8")).hexdigest()
    if got != want:
        raise ModelDigestError(
            f"native model content fails its embedded digest (want "
            f"sha256:{want[:12]}…, got sha256:{got[:12]}…): the file "
            "is torn or bit-flipped; refusing to load")
    return body


@dataclass
class HostTree:
    """One tree with real-valued thresholds, trimmed to its actual size."""
    split_feature: np.ndarray   # (m,) i32
    threshold: np.ndarray       # (m,) f64  (x <= threshold -> left)
    split_gain: np.ndarray      # (m,) f64
    left_child: np.ndarray      # (m,) i32  (>=0 node, <0 leaf ~idx)
    right_child: np.ndarray     # (m,) i32
    decision_type: np.ndarray   # (m,) i32
    leaf_value: np.ndarray      # (L,) f64
    leaf_weight: np.ndarray     # (L,) f64
    leaf_count: np.ndarray      # (L,) i64
    internal_value: np.ndarray  # (m,) f64
    internal_weight: np.ndarray  # (m,) f64
    internal_count: np.ndarray  # (m,) i64
    shrinkage: float = 1.0
    #: categorical splits (LightGBM layout): for a node with
    #: decision_type bit0 set, ``threshold`` holds an index j into
    #: ``cat_boundaries``; words ``cat_threshold[cat_boundaries[j]:
    #: cat_boundaries[j+1]]`` form a bitset over raw category values —
    #: bit set → value goes LEFT.
    num_cat: int = 0
    cat_boundaries: np.ndarray = field(
        default_factory=lambda: np.zeros(1, np.int32))
    cat_threshold: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.uint32))

    @property
    def num_leaves(self) -> int:
        return len(self.leaf_value)

    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        m = len(self.split_feature)
        depth = np.zeros(m, dtype=np.int64)
        out = 1
        for i in range(m):  # children always have larger node ids
            for c in (self.left_child[i], self.right_child[i]):
                if c >= 0:
                    depth[c] = depth[i] + 1
                    out = max(out, int(depth[c]) + 1)
        return out


def host_tree_from_arrays(tree: TreeArrays, mapper: BinMapper,
                          missing_bin: int) -> HostTree:
    """Trim a device TreeArrays to its actual size with real thresholds."""
    num_leaves = int(tree.num_leaves)
    m = max(num_leaves - 1, 0)
    feat = np.asarray(tree.node_feat)[:m]
    bins = np.asarray(tree.node_bin)[:m]
    is_cat = np.asarray(tree.node_is_cat)[:m] > 0
    cat_bits = np.asarray(tree.node_cat_bits)[:m]
    thr = np.array([mapper.bin_threshold_value(int(f), int(b))
                    for f, b in zip(feat, bins)], dtype=np.float64)
    # decision_type: numerical split; missing (NaN) routes right in training
    # (missing bin is the trailing bin), i.e. default_left = false.
    dt = np.where(mapper.has_missing[feat] if m else np.zeros(0, bool),
                  8, 2).astype(np.int32)  # 8 = missing:NaN, 2 = default-left
    num_cat = 0
    cat_boundaries = [0]
    cat_words: List[np.ndarray] = []
    if is_cat.any():
        for i in np.flatnonzero(is_cat):
            f_i = int(feat[i])
            cats = mapper.cat_values[f_i]
            bits = cat_bits[i]
            left_bins = [b for b in range(len(cats))
                         if (bits[b >> 5] >> (b & 31)) & 1]
            left_cats = sorted(int(cats[b]) for b in left_bins)
            missing_left = bool(
                (bits[missing_bin >> 5] >> (missing_bin & 31)) & 1)
            nwords = (max(left_cats, default=0) // 32) + 1
            words = np.zeros(nwords, np.uint32)
            for c in left_cats:
                words[c >> 5] |= np.uint32(1) << np.uint32(c & 31)
            dt[i] = 1 | (2 if missing_left else 0)
            thr[i] = float(num_cat)       # index into cat_boundaries
            cat_words.append(words)
            cat_boundaries.append(cat_boundaries[-1] + nwords)
            num_cat += 1
    return HostTree(
        split_feature=feat.astype(np.int32),
        threshold=thr,
        split_gain=np.asarray(tree.node_gain, np.float64)[:m],
        left_child=np.asarray(tree.node_left, np.int32)[:m],
        right_child=np.asarray(tree.node_right, np.int32)[:m],
        decision_type=dt,
        leaf_value=np.asarray(tree.leaf_value, np.float64)[:num_leaves],
        leaf_weight=np.asarray(tree.leaf_weight, np.float64)[:num_leaves],
        leaf_count=np.asarray(tree.leaf_count, np.float64)[:num_leaves]
            .astype(np.int64),
        internal_value=np.asarray(tree.node_value, np.float64)[:m],
        internal_weight=np.asarray(tree.node_weight, np.float64)[:m],
        internal_count=np.asarray(tree.node_count, np.float64)[:m]
            .astype(np.int64),
        num_cat=num_cat,
        cat_boundaries=np.asarray(cat_boundaries, np.int32),
        cat_threshold=(np.concatenate(cat_words).astype(np.uint32)
                       if cat_words else np.zeros(0, np.uint32)),
    )


class Booster:
    """A trained forest + objective metadata; predicts via jitted traversal."""

    def __init__(self, trees: List[HostTree], num_class: int = 1,
                 objective_str: str = "regression",
                 init_score: float = 0.0,
                 feature_names: Optional[List[str]] = None,
                 feature_infos: Optional[List[str]] = None,
                 max_feature_idx: Optional[int] = None,
                 params: Optional[Dict[str, str]] = None):
        self.trees = trees
        self.num_class = num_class
        self.objective_str = objective_str
        self.init_score = init_score
        self.max_feature_idx = max_feature_idx if max_feature_idx is not None \
            else (max((int(t.split_feature.max()) for t in trees
                       if len(t.split_feature)), default=0))
        nf = self.max_feature_idx + 1
        self.feature_names = feature_names or [
            f"Column_{i}" for i in range(nf)]
        self.feature_infos = feature_infos or ["none"] * nf
        self.params = params or {}
        self._stacked = None
        self._stacked_np = None
        # bumped whenever the stacked prediction cache is dropped; a
        # CompiledPredictor captures the token at build time and refuses
        # to score a forest that changed under it
        self._cache_token = 0
        # fit-time data-quality baseline (ISSUE 15): the engine attaches
        # a core.sketch.ReferenceProfile after training; the registry
        # persists it beside the model and drift monitors compare live
        # traffic against it.  None for loaded/extended models whose
        # profile wasn't captured — drift monitoring is simply off then.
        self.reference_profile = None

    def extended(self, continuation: "Booster") -> "Booster":
        """The merged model of continued training (LightGBM's
        ``init_model``): this booster's trees followed by the
        ``continuation`` forest that was trained with this booster's
        margins as init scores.  Predictions of the merged model equal
        base margins + continuation margins by additivity.  Reference:
        LightGBMBooster model round-trip + LightGBM's
        init_model/keep_training_booster capability (SURVEY.md §5.4)."""
        if continuation.num_class != self.num_class:
            raise ValueError(
                f"cannot extend a {self.num_class}-class model with a "
                f"{continuation.num_class}-class continuation")
        if continuation.max_feature_idx != self.max_feature_idx:
            raise ValueError(
                f"feature count mismatch: base model uses "
                f"{self.max_feature_idx + 1} features, continuation "
                f"{continuation.max_feature_idx + 1}")
        params = dict(continuation.params)
        old_it = len(self.trees) // max(self.num_class, 1)
        new_it = len(continuation.trees) // max(self.num_class, 1)
        params["num_iterations"] = str(old_it + new_it)
        return Booster(
            list(self.trees) + list(continuation.trees),
            num_class=self.num_class,
            objective_str=continuation.objective_str,
            init_score=self.init_score,
            feature_names=continuation.feature_names,
            feature_infos=continuation.feature_infos,
            max_feature_idx=self.max_feature_idx,
            params=params)

    # -- prediction ----------------------------------------------------------

    def invalidate_cache(self) -> None:
        """Drop the stacked prediction arrays.  Call after mutating
        ``trees`` in place; any outstanding :class:`CompiledPredictor`
        raises on its next call instead of silently scoring the old
        forest."""
        self._stacked = None
        self._stacked_np = None
        self._cache_token += 1

    def predictor(self, num_iteration: Optional[int] = None,
                  backend: str = "auto",
                  tree_range: Optional[Tuple[int, int]] = None,
                  include_init_score: bool = True
                  ) -> "CompiledPredictor":
        """Serving-hot-path margin scorer with all per-call dispatch
        (shape checks, ``_stack()`` dict indexing, ``use_t`` slicing,
        native-vs-jit backend probe) resolved ONCE at construction.
        ``backend``: "auto" (native when available on cpu, else jit),
        "native", or "jit" (force the XLA walk — the accelerator path,
        also what benchmarks pin for apples-to-apples comparisons).

        ``tree_range=(lo, hi)`` scores only trees ``lo..hi-1`` — the
        sharded scoring fleet's tree-range partial scorer (ISSUE 11).
        Bounds must align to ``num_class`` (shards hold whole boosting
        iterations, since tree→class assignment is positional).  With
        ``include_init_score=False`` the partial carries NO init score,
        so summing the shards' partials reproduces the full margin
        (shard 0 keeps the init score exactly once)."""
        return CompiledPredictor(self, num_iteration, backend,
                                 tree_range=tree_range,
                                 include_init_score=include_init_score)

    def _stack(self):
        """Pad trees to uniform arrays for a jitted scan."""
        if self._stacked is not None:
            return self._stacked
        T = len(self.trees)
        if T == 0:
            self._stacked = None
            return None
        m = max(max(len(t.split_feature) for t in self.trees), 1)
        L = max(max(t.num_leaves for t in self.trees), 1)
        depth = max(max(t.max_depth() for t in self.trees), 1)

        def pad(arrs, width, dtype, fill=0):
            out = np.full((T, width), fill, dtype=dtype)
            for i, a in enumerate(arrs):
                out[i, :len(a)] = a
            return out

        def thr32(t):
            # Round thresholds UP to float32 so the f32 decision `x <= thr`
            # agrees with the exact f64 threshold for every f32-representable
            # x (rounding down could flip a midpoint onto the right value).
            v = t.threshold.astype(np.float32)
            low = v.astype(np.float64) < t.threshold
            v[low] = np.nextafter(v[low], np.float32(np.inf))
            return v

        ncat_max = max(max(t.num_cat for t in self.trees), 1)
        words_max = max(max(len(t.cat_threshold) for t in self.trees), 1)
        stacked = {
            "feat": pad([t.split_feature for t in self.trees], m, np.int32),
            "thr": pad([thr32(t) for t in self.trees], m, np.float32),
            "left": pad([t.left_child for t in self.trees], m, np.int32),
            "right": pad([t.right_child for t in self.trees], m, np.int32),
            "leaf": pad([t.leaf_value for t in self.trees], L, np.float32),
            "single": np.array(
                [t.num_leaves <= 1 for t in self.trees], np.bool_),
            "is_cat": pad([(t.decision_type & 1).astype(np.int32)
                           for t in self.trees], m, np.int32),
            "dleft": pad([((t.decision_type & 2) >> 1).astype(np.int32)
                          for t in self.trees], m, np.int32),
            # zero-padded; padded entries are only read for numeric nodes
            # whose categorical branch result is discarded
            "cat_bnd": pad([t.cat_boundaries for t in self.trees],
                           ncat_max + 1, np.int32),
            "cat_words": pad([t.cat_threshold for t in self.trees],
                             words_max, np.uint32),
            "depth": depth,
            "has_cat": any(t.num_cat > 0 for t in self.trees),
        }
        # host copy retained only where the native scorer can use it —
        # on accelerators it would just double host memory per model
        self._stacked_np = stacked if jax.default_backend() == "cpu" \
            else None
        self._stacked = {k: (jnp.asarray(v) if isinstance(v, np.ndarray)
                             else v) for k, v in stacked.items()}
        return self._stacked

    def predict_margin(self, X, num_iteration: Optional[int] = None):
        """Raw margins: (n,) for single-class, (n, K) for multiclass."""
        shape = np.shape(X)
        if len(shape) != 2 or shape[1] <= self.max_feature_idx:
            raise ValueError(
                f"Model uses feature index {self.max_feature_idx} but input "
                f"has shape {shape}; expected (n, >= "
                f"{self.max_feature_idx + 1})")
        n = shape[0]
        s = self._stack()
        K = self.num_class
        if s is None:
            base = jnp.full((n,), self.init_score, jnp.float32)
            return jnp.tile(base[:, None], (1, K))[:, 0] if K == 1 else \
                jnp.tile(base[:, None], (1, K))
        T = s["feat"].shape[0]
        use_t = T if num_iteration is None else min(num_iteration * K, T)
        sn = self._stacked_np
        if sn is not None and not isinstance(X, jax.core.Tracer) \
                and jax.default_backend() == "cpu":
            from .. import native
            if native.predict_forest_available():
                Xnp = np.ascontiguousarray(np.asarray(X, np.float32))
                out = np.zeros((n, K), np.float32)
                native.predict_forest(
                    Xnp, sn["feat"][:use_t], sn["thr"][:use_t],
                    sn["left"][:use_t], sn["right"][:use_t],
                    sn["leaf"][:use_t], sn["single"][:use_t],
                    sn["is_cat"][:use_t], sn["dleft"][:use_t],
                    sn["cat_bnd"][:use_t], sn["cat_words"][:use_t],
                    K, sn["has_cat"], out)
                out += np.float32(self.init_score)
                return out[:, 0] if K == 1 else out
        X = jnp.asarray(X, jnp.float32)
        margins = _predict_forest(X, s["feat"][:use_t], s["thr"][:use_t],
                                  s["left"][:use_t], s["right"][:use_t],
                                  s["leaf"][:use_t], s["single"][:use_t],
                                  s["is_cat"][:use_t], s["dleft"][:use_t],
                                  s["cat_bnd"][:use_t],
                                  s["cat_words"][:use_t],
                                  s["depth"], K, s["has_cat"])
        margins = margins + self.init_score
        return margins[:, 0] if K == 1 else margins

    def predict(self, X, raw_score: bool = False,
                num_iteration: Optional[int] = None):
        m = self.predict_margin(X, num_iteration)
        if raw_score:
            return m
        obj = self.objective_str.split(" ")[0]
        if obj == "binary":
            sig = _param_from_str(self.objective_str, "sigmoid", 1.0)
            return jax.nn.sigmoid(sig * m)
        if obj in ("multiclass", "softmax"):
            return jax.nn.softmax(m, axis=-1)
        if obj in ("poisson", "gamma", "tweedie"):
            return jnp.exp(m)                    # log link
        if obj in ("cross_entropy", "xentropy"):
            return jax.nn.sigmoid(m)
        if obj == "multiclassova":
            sig = _param_from_str(self.objective_str, "sigmoid", 1.0)
            p = jax.nn.sigmoid(sig * m)
            return p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True),
                                   1e-12)
        return m

    def predict_contrib(self, X) -> np.ndarray:
        """Per-row TreeSHAP contributions, LightGBM pred_contrib layout:
        (n, num_class * (num_features + 1)) with the expected value in
        each class's trailing slot (see mmlspark_tpu/gbdt/shap.py)."""
        from .shap import predict_contrib
        return predict_contrib(self, X)

    def predict_leaf_index(self, X):
        X = jnp.asarray(X, jnp.float32)
        s = self._stack()
        if s is None:
            return jnp.zeros((X.shape[0], 0), jnp.int32)
        return _predict_leaves(X, s["feat"], s["thr"], s["left"], s["right"],
                               s["single"], s["is_cat"], s["dleft"],
                               s["cat_bnd"], s["cat_words"], s["depth"],
                               s["has_cat"])

    # -- feature importance --------------------------------------------------

    def feature_importances(self, importance_type: str = "split"):
        nf = self.max_feature_idx + 1
        out = np.zeros(nf)
        for t in self.trees:
            if importance_type == "gain":
                np.add.at(out, t.split_feature, t.split_gain)
            else:
                np.add.at(out, t.split_feature, 1.0)
        return out

    # -- LightGBM text-format interop (SURVEY.md §5.4 contract) --------------

    def save_native_model_string(self) -> str:
        buf = io.StringIO()
        nf = self.max_feature_idx + 1
        buf.write("tree\n")
        buf.write("version=v3\n")
        buf.write(f"num_class={self.num_class}\n")
        buf.write(f"num_tree_per_iteration={self.num_class}\n")
        buf.write("label_index=0\n")
        buf.write(f"max_feature_idx={self.max_feature_idx}\n")
        buf.write(f"objective={self.objective_str}\n")
        buf.write("feature_names=" + " ".join(self.feature_names[:nf]) + "\n")
        buf.write("feature_infos=" + " ".join(self.feature_infos[:nf]) + "\n")

        tree_bufs = []
        for i, t in enumerate(self.trees):
            tb = io.StringIO()
            tb.write(f"Tree={i}\n")
            tb.write(f"num_leaves={t.num_leaves}\n")
            tb.write(f"num_cat={t.num_cat}\n")
            if t.num_leaves > 1:
                tb.write(_arr_line("split_feature", t.split_feature))
                tb.write(_arr_line("split_gain", t.split_gain))
                tb.write(_arr_line("threshold", t.threshold))
                tb.write(_arr_line("decision_type", t.decision_type))
                tb.write(_arr_line("left_child", t.left_child))
                tb.write(_arr_line("right_child", t.right_child))
                tb.write(_arr_line("leaf_value", t.leaf_value))
                tb.write(_arr_line("leaf_weight", t.leaf_weight))
                tb.write(_arr_line("leaf_count", t.leaf_count))
                tb.write(_arr_line("internal_value", t.internal_value))
                tb.write(_arr_line("internal_weight", t.internal_weight))
                tb.write(_arr_line("internal_count", t.internal_count))
                if t.num_cat > 0:
                    tb.write(_arr_line("cat_boundaries", t.cat_boundaries))
                    tb.write(_arr_line("cat_threshold", t.cat_threshold))
            else:
                tb.write(_arr_line("leaf_value", t.leaf_value))
            tb.write("is_linear=0\n")
            tb.write(f"shrinkage={t.shrinkage:g}\n")
            tb.write("\n\n")
            tree_bufs.append(tb.getvalue())

        buf.write("tree_sizes=" + " ".join(
            str(len(tb.encode("utf-8"))) for tb in tree_bufs) + "\n\n")
        for tb in tree_bufs:
            buf.write(tb)
        buf.write("end of trees\n\n")
        buf.write("feature_importances:\n")
        imp = self.feature_importances("gain")
        order = np.argsort(-imp)
        for j in order:
            if imp[j] > 0:
                buf.write(f"{self.feature_names[j]}={imp[j]:g}\n")
        buf.write("\nparameters:\n")
        for k, v in self.params.items():
            buf.write(f"[{k}: {v}]\n")
        buf.write("end of parameters\n")
        return buf.getvalue()

    def save_native_model(self, path: str) -> None:
        """Write the native-model text with the content-digest header
        (:data:`DIGEST_HEADER`) prepended, so any later load detects a
        torn or bit-flipped file instead of serving it.  The header is
        one comment line; ``save_native_model_string`` stays the bare
        interop text."""
        with open(path, "w") as f:
            f.write(with_digest_header(self.save_native_model_string()))

    @classmethod
    def load_native_model_string(cls, text: str) -> "Booster":
        # digest header (when present) is verified and stripped FIRST:
        # corrupt bytes raise ModelDigestError before any parsing
        text = split_native_digest(text)
        header, _, rest = text.partition("Tree=")
        head = _parse_kv(header)
        num_class = int(head.get("num_class", 1))
        objective = head.get("objective", "regression")
        feature_names = head.get("feature_names", "").split()
        feature_infos = head.get("feature_infos", "").split()
        max_feature_idx = int(head.get("max_feature_idx", 0))

        trees: List[HostTree] = []
        body = rest.split("end of trees")[0]
        blocks = re.split(r"Tree=\d+\n", "Tree=" + body)
        for block in blocks:
            block = block.strip()
            if not block or block == "Tree=":
                continue
            kv = _parse_kv(block)
            if "num_leaves" not in kv:
                continue
            L = int(kv["num_leaves"])
            num_cat = int(kv.get("num_cat", 0))
            if L > 1:
                dt = _parse_arr(kv["decision_type"], np.int32)
                trees.append(HostTree(
                    split_feature=_parse_arr(kv["split_feature"], np.int32),
                    threshold=_parse_arr(kv["threshold"], np.float64),
                    split_gain=_parse_arr(
                        kv.get("split_gain", "0"), np.float64),
                    left_child=_parse_arr(kv["left_child"], np.int32),
                    right_child=_parse_arr(kv["right_child"], np.int32),
                    decision_type=dt,
                    leaf_value=_parse_arr(kv["leaf_value"], np.float64),
                    leaf_weight=_parse_arr(
                        kv.get("leaf_weight", "0"), np.float64),
                    leaf_count=_parse_arr(
                        kv.get("leaf_count", "0"), np.int64),
                    internal_value=_parse_arr(
                        kv.get("internal_value", "0"), np.float64),
                    internal_weight=_parse_arr(
                        kv.get("internal_weight", "0"), np.float64),
                    internal_count=_parse_arr(
                        kv.get("internal_count", "0"), np.int64),
                    shrinkage=float(kv.get("shrinkage", 1.0)),
                    num_cat=num_cat,
                    cat_boundaries=(_parse_arr(kv["cat_boundaries"],
                                               np.int64).astype(np.int32)
                                    if num_cat > 0
                                    else np.zeros(1, np.int32)),
                    cat_threshold=(_parse_arr(kv["cat_threshold"],
                                              np.int64).astype(np.uint32)
                                   if num_cat > 0
                                   else np.zeros(0, np.uint32)),
                ))
            else:
                lv = _parse_arr(kv["leaf_value"], np.float64)
                trees.append(HostTree(
                    split_feature=np.zeros(0, np.int32),
                    threshold=np.zeros(0, np.float64),
                    split_gain=np.zeros(0, np.float64),
                    left_child=np.zeros(0, np.int32),
                    right_child=np.zeros(0, np.int32),
                    decision_type=np.zeros(0, np.int32),
                    leaf_value=lv,
                    leaf_weight=np.zeros(1, np.float64),
                    leaf_count=np.zeros(1, np.int64),
                    internal_value=np.zeros(0, np.float64),
                    internal_weight=np.zeros(0, np.float64),
                    internal_count=np.zeros(0, np.int64),
                    shrinkage=float(kv.get("shrinkage", 1.0)),
                ))
        return cls(trees, num_class=num_class, objective_str=objective,
                   init_score=0.0, feature_names=feature_names or None,
                   feature_infos=feature_infos or None,
                   max_feature_idx=max_feature_idx)

    @classmethod
    def load_native_model(cls, path: str) -> "Booster":
        with open(path, "rb") as f:
            raw = f.read()
        try:
            text = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            # a digest-stamped file may decode with replacement
            # characters: they alter the body, so the digest check
            # below rejects the file with the right verdict
            # (ModelDigestError, not UnicodeDecodeError).  A
            # digest-less legacy file has no such net — replacement
            # characters would be silently PARSED — so refuse it
            # outright instead of accepting mangled bytes.
            head = raw[:len(DIGEST_HEADER) + 16]
            if raw.startswith(DIGEST_HEADER.encode("utf-8")) \
                    or b".digest.sha256=" in head:
                text = raw.decode("utf-8", errors="replace")
            else:
                raise ModelDigestError(
                    f"native model file {path!r} is not valid UTF-8 "
                    "and carries no digest header; the file is torn "
                    "or binary-corrupted — refusing to load") from e
        return cls.load_native_model_string(text)


class CompiledPredictor:
    """Margin scorer with the prediction path resolved once.

    ``Booster.predict_margin`` re-does shape checks, ``_stack()`` dict
    indexing, ``use_t`` slicing, and the native-vs-jit backend probe on
    EVERY call — pure overhead at serving batch sizes where the walk
    itself is microseconds.  This captures the resolved dispatch at
    construction: pre-sliced stacked arrays, the chosen backend, and the
    class/init-score constants.  Margins are bit-exact with
    ``predict_margin`` (the native path and the jitted walk are pinned
    against each other in tests/test_native_forest.py; this class only
    removes per-call resolution, not arithmetic).

    Staleness contract: the predictor is bound to the forest it was
    built from.  ``Booster.invalidate_cache()`` (required after mutating
    ``trees`` in place) bumps a token; a stale predictor raises
    ``RuntimeError`` on its next call instead of silently scoring the
    old forest.  ``Booster.extended()`` and model loads return NEW
    boosters (with a fresh, empty cache), so predictors of the base
    model stay valid for the base forest.
    """

    def __init__(self, booster: Booster,
                 num_iteration: Optional[int] = None,
                 backend: str = "auto",
                 tree_range: Optional[Tuple[int, int]] = None,
                 include_init_score: bool = True):
        if backend not in ("auto", "native", "jit"):
            raise ValueError(f"backend must be auto|native|jit, "
                             f"got {backend!r}")
        self._booster = booster
        self._token = booster._cache_token
        self._num_trees = len(booster.trees)
        self._K = booster.num_class
        self._init_score = booster.init_score if include_init_score \
            else 0.0
        self.num_features = booster.max_feature_idx + 1
        self.num_iteration = num_iteration
        self.tree_range = tree_range
        s = booster._stack()
        if s is None:
            self._mode = "empty"
            return
        T = s["feat"].shape[0]
        if tree_range is not None:
            # tree-range partial scorer (the fleet's shard slice):
            # bounds must land on num_class boundaries because BOTH
            # walkers assign class = local tree index % K — a
            # misaligned lo would silently rotate classes
            if num_iteration is not None:
                raise ValueError(
                    "pass num_iteration OR tree_range, not both")
            lo, hi = int(tree_range[0]), int(tree_range[1])
            if not 0 <= lo <= hi <= T:
                raise ValueError(
                    f"tree_range {tree_range} outside [0, {T}]")
            if lo % self._K or (hi % self._K and hi != T):
                raise ValueError(
                    f"tree_range {tree_range} must align to "
                    f"num_class={self._K} boundaries")
            if lo == hi:
                self._mode = "empty"
                return
            sl = slice(lo, hi)
        else:
            use_t = T if num_iteration is None \
                else min(num_iteration * self._K, T)
            sl = slice(0, use_t)
        sn = booster._stacked_np
        from .. import native
        native_ok = sn is not None and jax.default_backend() == "cpu" \
            and native.predict_forest_available()
        if backend == "native" and not native_ok:
            raise RuntimeError(
                "backend='native' requested but the native forest "
                "scorer is unavailable on this backend")
        if backend != "jit" and native_ok:
            self._mode = "native"
            self._nargs = (sn["feat"][sl], sn["thr"][sl],
                           sn["left"][sl], sn["right"][sl],
                           sn["leaf"][sl], sn["single"][sl],
                           sn["is_cat"][sl], sn["dleft"][sl],
                           sn["cat_bnd"][sl], sn["cat_words"][sl])
            self._has_cat = sn["has_cat"]
        else:
            self._mode = "jit"
            self._jargs = (s["feat"][sl], s["thr"][sl],
                           s["left"][sl], s["right"][sl],
                           s["leaf"][sl], s["single"][sl],
                           s["is_cat"][sl], s["dleft"][sl],
                           s["cat_bnd"][sl], s["cat_words"][sl])
            self._depth = s["depth"]
            self._has_cat = s["has_cat"]

    @property
    def mode(self) -> str:
        """Resolved backend: 'native', 'jit', or 'empty'."""
        return self._mode

    def _check_fresh(self) -> None:
        b = self._booster
        if b._cache_token != self._token \
                or len(b.trees) != self._num_trees:
            raise RuntimeError(
                "stale CompiledPredictor: the bound Booster's forest "
                "changed after this predictor was built (invalidate_"
                "cache() was called or trees were added); rebuild with "
                "booster.predictor()")

    def __call__(self, X):
        """Raw margins, bit-exact with ``predict_margin``: (n,) float32
        for single-class, (n, K) for multiclass."""
        self._check_fresh()
        shape = np.shape(X)
        if len(shape) != 2 or shape[1] < self.num_features:
            raise ValueError(
                f"Model uses feature index {self.num_features - 1} but "
                f"input has shape {shape}; expected (n, >= "
                f"{self.num_features})")
        n = shape[0]
        K = self._K
        if self._mode == "empty":
            base = jnp.full((n,), self._init_score, jnp.float32)
            return jnp.tile(base[:, None], (1, K))[:, 0] if K == 1 else \
                jnp.tile(base[:, None], (1, K))
        if self._mode == "native":
            from .. import native
            Xnp = np.ascontiguousarray(np.asarray(X, np.float32))
            out = np.zeros((n, K), np.float32)
            native.predict_forest(Xnp, *self._nargs, K, self._has_cat,
                                  out)
            out += np.float32(self._init_score)
            return out[:, 0] if K == 1 else out
        X = jnp.asarray(X, jnp.float32)
        margins = _predict_forest(X, *self._jargs, self._depth, K,
                                  self._has_cat)
        margins = margins + self._init_score
        return margins[:, 0] if K == 1 else margins


def _arr_line(name: str, arr: np.ndarray) -> str:
    if arr.dtype.kind == "f":
        vals = " ".join(np.format_float_positional(
            v, precision=17, trim="0") for v in arr)
    else:
        vals = " ".join(str(int(v)) for v in arr)
    return f"{name}={vals}\n"


def _parse_kv(block: str) -> Dict[str, str]:
    out = {}
    for line in block.splitlines():
        if "=" in line:
            k, _, v = line.partition("=")
            out[k.strip()] = v.strip()
    return out


def _parse_arr(s: str, dtype) -> np.ndarray:
    if not s:
        return np.zeros(0, dtype)
    return np.array(s.split(), dtype=np.float64).astype(dtype)


def _param_from_str(s: str, key: str, default: float) -> float:
    m = re.search(rf"{key}:([0-9.eE+-]+)", s)
    return float(m.group(1)) if m else default


def _cat_go_left(x, j, tdleft_node, cat_bnd, cat_words):
    """Raw-value categorical decision: x in node j's bitset → left.

    NaN routes by the node's default_left bit; negative / out-of-range
    values (unseen categories) route right, matching LightGBM.
    """
    j = jnp.clip(j, 0, cat_bnd.shape[0] - 2)
    b0 = cat_bnd[j]
    b1 = cat_bnd[j + 1]
    xnan = jnp.isnan(x)
    c = jnp.where(xnan, -1.0, x).astype(jnp.int32)
    widx = b0 + (c >> 5)
    ok = (c >= 0) & (widx < b1)
    word = cat_words[jnp.clip(widx, 0, cat_words.shape[0] - 1)]
    bit = ((word >> (c & 31).astype(jnp.uint32)) & 1).astype(bool)
    return jnp.where(xnan, tdleft_node > 0, ok & bit)


@functools.partial(jax.jit,
                   static_argnames=("depth", "num_class", "has_cat"))
def _predict_forest(X, feat, thr, left, right, leaf, single, is_cat, dleft,
                    cat_bnd, cat_words, depth, num_class, has_cat=True):
    """Sum tree outputs: scan over trees, fixed-depth gather walk per tree."""
    n = X.shape[0]
    K = num_class

    def one_tree(carry, tree):
        scores = carry
        (tfeat, tthr, tleft, tright, tleaf, tsingle, tcat, tdleft,
         tbnd, twords, k) = tree
        node = jnp.where(tsingle, jnp.full(n, -1, jnp.int32),
                         jnp.zeros(n, jnp.int32))

        def body(_, node):
            is_leaf = node < 0
            safe = jnp.maximum(node, 0)
            f = tfeat[safe]
            x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            go_left = x <= tthr[safe]
            if has_cat:  # static: numeric-only forests skip the bitset walk
                left_cat = _cat_go_left(x, tthr[safe].astype(jnp.int32),
                                        tdleft[safe], tbnd, twords)
                go_left = jnp.where(tcat[safe] > 0, left_cat, go_left)
            nxt = jnp.where(go_left, tleft[safe], tright[safe])
            return jnp.where(is_leaf, node, nxt)

        node = jax.lax.fori_loop(0, depth, body, node)
        vals = tleaf[-(node + 1)]
        scores = scores.at[:, k].add(vals)
        return scores, None

    ks = jnp.arange(feat.shape[0], dtype=jnp.int32) % K
    init = jnp.zeros((n, K), jnp.float32)
    out, _ = jax.lax.scan(one_tree, init,
                          (feat, thr, left, right, leaf, single, is_cat,
                           dleft, cat_bnd, cat_words, ks))
    return out


@functools.partial(jax.jit, static_argnames=("depth", "has_cat"))
def _predict_leaves(X, feat, thr, left, right, single, is_cat, dleft,
                    cat_bnd, cat_words, depth, has_cat=True):
    n = X.shape[0]

    def one_tree(_, tree):
        tfeat, tthr, tleft, tright, tsingle, tcat, tdleft, tbnd, twords = \
            tree
        node = jnp.where(tsingle, jnp.full(n, -1, jnp.int32),
                         jnp.zeros(n, jnp.int32))

        def body(_, node):
            is_leaf = node < 0
            safe = jnp.maximum(node, 0)
            f = tfeat[safe]
            x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            go_left = x <= tthr[safe]
            if has_cat:  # static: numeric-only forests skip the bitset walk
                left_cat = _cat_go_left(x, tthr[safe].astype(jnp.int32),
                                        tdleft[safe], tbnd, twords)
                go_left = jnp.where(tcat[safe] > 0, left_cat, go_left)
            nxt = jnp.where(go_left, tleft[safe], tright[safe])
            return jnp.where(is_leaf, node, nxt)

        node = jax.lax.fori_loop(0, depth, body, node)
        return None, -(node + 1)

    _, leaves = jax.lax.scan(one_tree, None,
                             (feat, thr, left, right, single, is_cat,
                              dleft, cat_bnd, cat_words))
    return leaves.T.astype(jnp.int32)
