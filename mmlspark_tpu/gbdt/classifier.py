"""LightGBMClassifier / LightGBMClassificationModel.

TPU-native re-implementation of the reference's north-star estimator
(lightgbm/LightGBMClassifier.scala, expected path, UNVERIFIED; SURVEY.md
§2.1, §3.1-3.2).  API mirrors the reference: binary and multiclass, output
columns rawPrediction (margin vector), probability, prediction.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.params import (Param, TypeConverters, HasProbabilityCol,
                           HasRawPredictionCol)
from ..core.schema import DataTable, features_matrix
from .base import LightGBMBase, LightGBMModelBase
from .booster import Booster


class _ClassifierParams(HasProbabilityCol, HasRawPredictionCol):
    isUnbalance = Param("isUnbalance",
                        "Up-weight the rare class in binary training",
                        default=False, typeConverter=TypeConverters.toBool)
    scalePosWeight = Param("scalePosWeight", "Weight of positive class",
                           default=1.0, typeConverter=TypeConverters.toFloat)
    sigmoid = Param("sigmoid", "Sigmoid scaling for binary objective",
                    default=1.0, typeConverter=TypeConverters.toFloat)
    thresholds = Param("thresholds",
                       "Per-class prediction thresholds (optional)",
                       default=None, typeConverter=TypeConverters.toListFloat)


class LightGBMClassifier(LightGBMBase, _ClassifierParams):
    _default_objective = "binary"

    def __init__(self, **kwargs):
        kwargs.setdefault("objective", "binary")
        super().__init__(**kwargs)
        self._num_class = 1

    def _objective_kwargs(self):
        return dict(sigmoid=self.getSigmoid(),
                    is_unbalance=self.getIsUnbalance(),
                    scale_pos_weight=self.getScalePosWeight())

    def _prepare_labels(self, y):
        y = np.asarray(y)
        self._num_class = 1
        if self.getObjective() in ("multiclass", "softmax",
                                   "multiclassova", "ova"):
            self._resolved_objective = self.getObjective()
            if y.dtype.kind == "f" and np.isnan(y).any():
                # must fail HERE: the int cast below would turn NaN into
                # an arbitrary class id and train silently on garbage
                # (LightGBM likewise rejects NaN labels)
                raise ValueError(
                    "multiclass labels contain NaN; labels must be "
                    "integer class ids in [0, num_class)")
            return y.astype(np.int64)
        if self.getObjective() in ("cross_entropy", "xentropy"):
            # soft probability labels: no 0/1 coercion, no multiclass
            # auto-promotion (LightGBM xentropy accepts y in [0, 1])
            self._resolved_objective = self.getObjective()
            y = y.astype(np.float64)
            if np.isnan(y).any() or y.min() < 0 or y.max() > 1:
                raise ValueError(
                    "cross_entropy labels must lie in [0, 1]")
            return y
        uniq = np.unique(y[~np.isnan(y.astype(np.float64))]) \
            if y.dtype.kind == "f" else np.unique(y)
        if len(uniq) > 2:
            # auto-promote to multiclass like the reference wrapper does
            # (kept off the param map: fit must not mutate the estimator)
            self._resolved_objective = "multiclass"
            self._num_class = int(np.max(y)) + 1
            return y.astype(np.int64)
        self._resolved_objective = self.getObjective()
        return y.astype(np.float64)

    def _val_metric(self):
        obj = getattr(self, "_resolved_objective", self.getObjective())

        if obj in ("multiclass", "softmax", "multiclassova", "ova"):
            def logloss_mc(scores, labels, weights):
                p = _softmax(scores)
                n = len(labels)
                eps = 1e-15
                ll = -np.log(np.clip(
                    p[np.arange(n), labels.astype(int)], eps, 1.0))
                if weights is not None:
                    return float(np.average(ll, weights=weights))
                return float(np.mean(ll))
            return logloss_mc

        sig = self.getSigmoid()

        def logloss(scores, labels, weights):
            p = 1.0 / (1.0 + np.exp(-sig * scores))
            eps = 1e-15
            p = np.clip(p, eps, 1 - eps)
            ll = -(labels * np.log(p) + (1 - labels) * np.log(1 - p))
            if weights is not None:
                return float(np.average(ll, weights=weights))
            return float(np.mean(ll))
        return logloss

    def _make_model(self, booster: Booster) -> "LightGBMClassificationModel":
        return LightGBMClassificationModel(booster=booster)


class LightGBMClassificationModel(LightGBMModelBase, _ClassifierParams):

    def _transform(self, table: DataTable) -> DataTable:
        X = features_matrix(table, self.getFeaturesCol())
        margins = np.asarray(self._booster.predict_margin(X))
        if margins.ndim == 1:  # binary -> 2-class vectors
            raw = np.stack([-margins, margins], axis=1)
            sig = self.getSigmoid()
            p1 = 1.0 / (1.0 + np.exp(-sig * margins))
            prob = np.stack([1.0 - p1, p1], axis=1)
        else:
            raw = margins
            prob = _softmax(margins)
        thresholds = self.getThresholds()
        if thresholds:
            pred = np.argmax(prob / np.asarray(thresholds)[None, :], axis=1)
        else:
            pred = np.argmax(prob, axis=1)
        out = self._with_shap(table, X)
        raw_col = self.getRawPredictionCol()
        prob_col = self.getProbabilityCol()
        if raw_col:
            out = out.withColumn(raw_col, raw)
        if prob_col:
            out = out.withColumn(prob_col, prob)
        return out.withColumn(self.getPredictionCol(), pred.astype(np.float64))

    @property
    def numClasses(self) -> int:
        return max(self._booster.num_class, 2)


def _softmax(x):
    z = x - np.max(x, axis=-1, keepdims=True)
    e = np.exp(z)
    return e / np.sum(e, axis=-1, keepdims=True)
