"""Exclusive Feature Bundling (EFB) — LightGBM's sparse-feature fusion.

Ke et al. 2017 §4 / LightGBM ``enable_bundle``: features that are (near-)
mutually exclusive — at most one of them non-default per row, the shape
one-hot blocks take — are merged into a single **bundle** column whose
value encodes *which* member is non-default and *its* bin.  Histogram
construction then touches ``G`` bundle columns instead of ``f`` feature
columns; per-feature histograms are recovered exactly by slicing the
bundle histogram and reconstituting each member's default bin from leaf
totals (reference path: LightGBM ``src/io/dataset.cc`` FastFeatureBundling
+ ``FeatureGroup``; expected, UNVERIFIED).  Trees still reference
ORIGINAL features — EFB is a storage/compute optimization, invisible to
split finding, model export, and prediction.

Encoding of a bundle with members ``j`` (widths ``w_j = nb_j + 1``, the
``+1`` slot carrying the member's NaN/missing bin) at offsets ``off_j``
(cumulative, starting at 1):

* all members default        → 0
* member j at value bin b    → off_j + b          (b != default_j)
* member j missing (NaN)     → off_j + nb_j

Rows violating exclusivity (allowed up to ``max_conflict_rate``) keep the
first non-default member — the same information loss LightGBM accepts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class BundleSpec:
    """Static bundling plan.  Per-feature arrays are tuples so the spec
    can ride a hashable ``GrowerConfig`` as a jit-static argument."""
    bundles: Tuple[Tuple[int, ...], ...]   # bundle -> member feature ids
    bundle_of: Tuple[int, ...]             # feature -> bundle id
    off_of: Tuple[int, ...]                # feature -> offset in bundle
    nb_of: Tuple[int, ...]                 # feature -> value-bin count
    default_of: Tuple[int, ...]            # feature -> default bin

    @property
    def num_bundles(self) -> int:
        return len(self.bundles)

    @property
    def num_features(self) -> int:
        return len(self.bundle_of)

    @property
    def is_trivial(self) -> bool:
        """True when no bundle holds more than one feature."""
        return all(len(b) <= 1 for b in self.bundles)


def find_bundles(bins: np.ndarray, nb_of: List[int], missing_bin: int,
                 max_conflict_rate: float = 0.0,
                 max_bundle_bins: int = 256,
                 sample_cnt: int = 50_000,
                 seed: int = 0) -> BundleSpec:
    """Greedy bundling plan from a binned sample (GreedyBundle analog).

    ``nb_of[j]``: value bins actually used by feature j (excl. missing).
    Features are scanned by non-default density (densest first, LightGBM
    order); one goes into the first bundle where (a) the added pairwise
    conflicts stay within ``max_conflict_rate`` of the sample and (b) the
    bundle's total encoded width stays below ``max_bundle_bins``.
    """
    n, f = bins.shape
    if n > sample_cnt:
        idx = np.random.default_rng(seed).choice(n, sample_cnt,
                                                 replace=False)
        idx.sort()
        sample = bins[idx]
    else:
        sample = bins
    sn = sample.shape[0]
    default_of = []
    for j in range(f):
        col = sample[:, j]
        vals, counts = np.unique(col[col != missing_bin],
                                 return_counts=True)
        default_of.append(int(vals[np.argmax(counts)]) if len(vals)
                          else 0)
    default_arr = np.asarray(default_of)
    nondef = sample != default_arr[None, :]              # (sn, f) bool
    # pairwise conflict counts in one matmul (f x f fits easily for the
    # few-thousand-feature datasets EFB targets)
    nd = nondef.astype(np.float32)
    conflicts = nd.T @ nd                                 # (f, f)
    density = nd.sum(axis=0)

    budget = max_conflict_rate * sn
    order = np.argsort(-density, kind="stable")
    bundles: List[List[int]] = []
    bundle_conflict = []                                   # used budget
    widths = []                                            # encoded bins
    bundle_of = np.zeros(f, np.int64)
    for j in order:
        w_j = nb_of[j] + 1                                 # + missing slot
        placed = False
        for g, members in enumerate(bundles):
            add = float(sum(conflicts[j, m] for m in members))
            if (bundle_conflict[g] + add <= budget
                    and widths[g] + w_j < max_bundle_bins):
                members.append(int(j))
                bundle_conflict[g] += add
                widths[g] += w_j
                bundle_of[j] = g
                placed = True
                break
        if not placed:
            bundles.append([int(j)])
            bundle_conflict.append(0.0)
            widths.append(1 + w_j)        # slot 0 = all-default
            bundle_of[j] = len(bundles) - 1

    off_of = np.zeros(f, np.int64)
    eff_nb = np.asarray(nb_of, np.int64).copy()
    for g, members in enumerate(bundles):
        if len(members) == 1:
            # solo features keep IDENTITY encoding (offset 0, nb spanning
            # the whole bin range so the missing bin passes through) —
            # a dense 255-bin feature re-encoded with an offset would
            # overflow the uint8 bundle range
            eff_nb[members[0]] = max_bundle_bins - 1
            off_of[members[0]] = 0
            continue
        off = 1
        for j in members:
            off_of[j] = off
            off += nb_of[j] + 1
    return BundleSpec(
        bundles=tuple(tuple(m) for m in bundles),
        bundle_of=tuple(int(x) for x in bundle_of),
        off_of=tuple(int(x) for x in off_of),
        nb_of=tuple(int(x) for x in eff_nb),
        default_of=tuple(int(x) for x in default_of))


def bundle_matrix(bins: np.ndarray, spec: BundleSpec,
                  missing_bin: int) -> np.ndarray:
    """(n, f) binned matrix → (n, G) bundled matrix (uint8).

    First non-default member wins on (rare, budgeted) conflict rows."""
    n = bins.shape[0]
    out = np.zeros((n, spec.num_bundles), np.uint8)
    claimed = np.zeros((n, spec.num_bundles), bool)
    solo = {g for g, m in enumerate(spec.bundles) if len(m) == 1}
    for j in range(spec.num_features):
        g = spec.bundle_of[j]
        col = bins[:, j]
        if g in solo:
            out[:, g] = col.astype(np.uint8)
            continue
        default, nb, off = (spec.default_of[j], spec.nb_of[j],
                            spec.off_of[j])
        enc = np.where(col == missing_bin, off + nb,
                       off + col.astype(np.int64))
        nondef = (col != default) & ~claimed[:, g]
        out[nondef, g] = enc[nondef].astype(np.uint8)
        claimed[:, g] |= (col != default)
    return out


def expansion_arrays(spec: BundleSpec, num_bins: int, missing_bin: int):
    """Static numpy index maps for in-jit histogram expansion and split-
    column reconstruction.

    Returns ``(gather_idx, valid, bundle_of, off_of, nb_of, default_of)``
    where ``gather_idx[j, b]`` flat-indexes (bundle, bundle_bin) for
    original feature j's bin b (missing bin included), and ``valid``
    masks bins feature j doesn't use."""
    f, B = spec.num_features, num_bins
    gather_idx = np.zeros((f, B), np.int64)
    valid = np.zeros((f, B), bool)
    solo = {g for g, m in enumerate(spec.bundles) if len(m) == 1}
    for j in range(spec.num_features):
        g, off, nb = spec.bundle_of[j], spec.off_of[j], spec.nb_of[j]
        if g in solo:
            # identity mapping: the bundle column IS the feature column,
            # so every bin (default and missing included) carries its own
            # mass and the deficit correction contributes exactly zero
            gather_idx[j] = g * B + np.arange(B)
            valid[j] = True
            continue
        for b in range(nb):
            gather_idx[j, b] = g * B + off + b
            valid[j, b] = True
        gather_idx[j, missing_bin] = g * B + off + nb
        valid[j, missing_bin] = True
        # the default bin's slot (off + default) never receives rows —
        # its mass is reconstituted from leaf totals by the caller
    return (gather_idx, valid,
            np.asarray(spec.bundle_of, np.int32),
            np.asarray(spec.off_of, np.int32),
            np.asarray(spec.nb_of, np.int32),
            np.asarray(spec.default_of, np.int32))
