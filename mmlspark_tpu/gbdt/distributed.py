"""Distributed GBDT training over a device mesh.

This module replaces the reference's entire distributed-training machinery
(SURVEY.md §3.1, §5.8): driver-socket rendezvous → ``jax.distributed`` /
mesh construction; LightGBM's TCP ``Network::Allreduce`` of per-feature
histograms (Bruck allgather + recursive-halving reduce-scatter) →
``jax.lax.psum`` over the ``data`` mesh axis, compiler-scheduled onto ICI.

Parallelism mapping (reference ``parallelism`` param → mesh axes):

* ``data``    — rows sharded over the ``data`` axis; per-shard histograms
  psum-reduced; split finding replicated (LightGBM data-parallel learner).
* ``feature`` — features sharded over the ``feature`` axis; each shard scans
  its feature slice for candidate splits, the winner is all-gathered and the
  owning shard broadcasts the split column (LightGBM feature-parallel
  learner).  This is the GBDT analog of sequence parallelism: the wide axis
  is sharded (SURVEY.md §5.7).
* ``data+feature`` — 2-D mesh composing both.
* ``voting``  — data-sharded layout with PV-Tree split finding (Meng et
  al. 2016; LightGBM tree_learner=voting): histograms stay shard-local,
  each shard votes its top-k features, and only the ~2k winning features'
  histogram slices are psum-reduced (grower.find_best_split_voting).

The whole boost step (grad/hess → grow tree → score update) runs inside one
``shard_map`` under ``jit``, so a single compiled program per iteration does
compute + collectives with no host round-trips.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.mesh import DATA_AXIS, FEATURE_AXIS
from .grower import (GrowerConfig, TreeArrays, _grow_tree_impl,
                     apply_shrinkage, predict_tree_binned,
                     predict_tree_binned_fshard)
from .objectives import Objective


from ..core.mesh import shard_map_compat as _shard_map  # noqa: E402
# (the shim lives in core.mesh so the ops-layer ring collectives can
#  share it without an ops -> gbdt import inversion)


VALID_PARALLELISM = ("serial", "data", "feature", "data+feature", "voting")


def resolve_mesh(parallelism: str, mesh: Optional[Mesh] = None) -> Mesh:
    """Build the mesh shape implied by the ``parallelism`` param."""
    if mesh is not None:
        return mesh
    if parallelism not in VALID_PARALLELISM:
        raise ValueError(f"Unknown parallelism {parallelism!r}; "
                         f"valid: {VALID_PARALLELISM}")
    devs = jax.devices()
    n = len(devs)
    if parallelism == "feature" and n > 1:
        arr = np.asarray(devs).reshape(1, n)
    elif parallelism == "serial":
        arr = np.asarray(devs[:1]).reshape(1, 1)
    elif parallelism == "data+feature" and n > 1 and n % 2 == 0:
        arr = np.asarray(devs).reshape(n // 2, 2)
    else:  # data / voting (same mesh layout; voting differs in the grower)
        arr = np.asarray(devs).reshape(n, 1)
    return Mesh(arr, (DATA_AXIS, FEATURE_AXIS))


def data_only_mesh(mesh: Mesh) -> Mesh:
    """The same devices on a SINGLE-named-axis ``(data,)`` mesh.

    The Pallas ring collectives (ops/pallas_collectives.py) require
    exactly one named mesh axis — both for Mosaic's LOGICAL device-id
    lowering along the ring and for the interpret-mode DMA discharge,
    which rejects multi-axis environments.  Only meaningful for layouts
    whose feature axis is size 1 — pure data-parallel AND voting-
    parallel fits (voting shares the data layout; its voted-column ring
    reduces only the candidate slab) — and raises otherwise.  Every
    scan builder in this module sizes its PartitionSpecs via
    :func:`_f_ax`, so the rebuilt mesh flows through them unchanged."""
    if _feat_n(mesh) != 1:
        raise ValueError(
            "ring collectives need a pure data-parallel layout; "
            f"mesh has a feature axis of size {_feat_n(mesh)}")
    devs = np.asarray(mesh.devices).reshape(-1)
    return Mesh(devs, (DATA_AXIS,))


def _feat_n(mesh: Mesh) -> int:
    """Feature-axis size, 1 when the mesh is data-only (ring layout)."""
    return int(dict(mesh.shape).get(FEATURE_AXIS, 1))


def _f_ax(mesh: Mesh):
    """FEATURE_AXIS when the mesh carries one, else None — so the same
    PartitionSpecs build against both 2-axis and data-only meshes."""
    return FEATURE_AXIS if FEATURE_AXIS in dict(mesh.shape) else None


def _sharded_cfg(mesh: Mesh, cfg: GrowerConfig) -> GrowerConfig:
    data_n = int(mesh.shape[DATA_AXIS])
    feat_n = _feat_n(mesh)
    return GrowerConfig(**{
        **cfg.__dict__,
        "axis_name": DATA_AXIS if data_n > 1 else None,
        "feature_axis_name": FEATURE_AXIS if feat_n > 1 else None,
        "data_axis_size": data_n,
    })


def make_goss_scan(mesh: Mesh, obj: Objective, cfg: GrowerConfig, lr: float,
                   k1: int, k2: int, amp: float, has_val: bool = False,
                   num_class: int = 1):
    """Mesh GOSS: every data shard samples its own top-|g·h| rows plus an
    amplified random remainder (per-machine sampling, exactly like
    distributed LightGBM's boosting=goss), then the sampled sub-shards
    train one tree data-parallel with psum histograms.  ``k1``/``k2`` are
    PER-SHARD row counts; the per-iteration PRNG key is folded with the
    shard index so shards draw independent remainders.

    ``num_class > 1``: rows rank by the class-summed influence
    Σ_k |g_k·h_k| and one per-shard sample feeds all K class trees."""
    cfg = _sharded_cfg(mesh, cfg)
    K = num_class

    def tree_pred(tree, b):
        # train-side score update: with a feature axis each shard holds a
        # column slice, so the walk assembles compare vectors by psum;
        # validation bins stay full-feature per shard (host-small) and
        # keep the local walk
        if cfg.feature_axis_name is not None:
            return predict_tree_binned_fshard(tree, b, cfg.num_leaves,
                                              cfg.feature_axis_name)
        return predict_tree_binned(tree, b, cfg.num_leaves)

    def steps(bins, scores, labels, weights, real, keys, fis,
              val_bins, val_scores):
        def body(carry, xs):
            scores, val_scores = carry
            key, fi = xs
            if cfg.axis_name is not None:
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(cfg.axis_name))
            g, h = obj.grad_hess(scores, labels, weights)
            g = g * (real if K == 1 else real[:, None])
            h = h * (real if K == 1 else real[:, None])
            n_local = g.shape[0]
            infl = (jnp.abs(g * h) if K == 1
                    else jnp.sum(jnp.abs(g * h), axis=1))
            rank = jnp.argsort(-infl)                # pads (0) sort last
            top_idx = rank[:k1]
            rest = rank[k1:]
            rk = jax.random.uniform(key, (n_local - k1,))
            other_idx = jnp.take(rest, jnp.argsort(rk)[:k2])
            idx = jnp.concatenate([top_idx, other_idx])
            amp_vec = jnp.concatenate([
                jnp.ones(k1, jnp.float32), jnp.full(k2, amp, jnp.float32)])
            valid = jnp.take(real, idx)
            bins_g = jnp.take(bins, idx, axis=0)
            if K == 1:
                gh = jnp.stack([jnp.take(g, idx) * amp_vec,
                                jnp.take(h, idx) * amp_vec,
                                valid], axis=1)
                tree, _ = _grow_tree_impl(bins_g, gh, fi, cfg)
                scores = scores + lr * tree_pred(tree, bins)
                trees = apply_shrinkage(tree, lr)
                if has_val:
                    val_scores = val_scores + predict_tree_binned(
                        trees, val_bins, cfg.num_leaves)
            else:
                trees_k = []
                for k in range(K):
                    gh = jnp.stack([jnp.take(g[:, k], idx) * amp_vec,
                                    jnp.take(h[:, k], idx) * amp_vec,
                                    valid], axis=1)
                    tree, _ = _grow_tree_impl(bins_g, gh, fi, cfg)
                    scores = scores.at[:, k].add(
                        lr * tree_pred(tree, bins))
                    tree = apply_shrinkage(tree, lr)
                    if has_val:
                        val_scores = val_scores.at[:, k].add(
                            predict_tree_binned(tree, val_bins,
                                                cfg.num_leaves))
                    trees_k.append(tree)
                trees = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *trees_k)
            if has_val:
                out_v = val_scores
            else:
                out_v = jnp.zeros((0,) if K == 1 else (0, K), jnp.float32)
            return (scores, val_scores), (trees, out_v)

        (scores, val_scores), (trees, val_hist) = jax.lax.scan(
            body, (scores, val_scores), (keys, fis))
        if K > 1:
            trees = jax.tree_util.tree_map(
                lambda a: a.reshape((-1,) + a.shape[2:]), trees)
        return trees, scores, val_scores, val_hist

    sc_spec = P(DATA_AXIS) if K == 1 else P(DATA_AXIS, None)
    if has_val:
        val_hist_spec = (P(None, DATA_AXIS) if K == 1
                         else P(None, DATA_AXIS, None))
    else:
        val_hist_spec = P(None, None) if K == 1 else P(None, None, None)
    fa = _f_ax(mesh)
    mapped = _shard_map(
        steps, mesh=mesh,
        in_specs=(P(DATA_AXIS, fa), sc_spec, P(DATA_AXIS),
                  P(DATA_AXIS), P(DATA_AXIS), P(None, None),
                  P(None, fa, None),
                  P(DATA_AXIS, None), sc_spec),
        out_specs=(P(), sc_spec, sc_spec, val_hist_spec),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(1, 8))


def make_boost_scan(mesh: Mesh, obj: Objective, cfg: GrowerConfig, lr: float,
                    bag_sharded: bool, has_val: bool = False,
                    rf: bool = False, efb=None):
    """Chunked distributed boosting: a ``lax.scan`` over iterations INSIDE
    the shard_map, so a whole chunk of trees trains in one launch with all
    histogram psums compiler-scheduled onto ICI (the reference's per-
    iteration socket allreduce, amortized to one program).

    ``rf``: random-forest mode — every tree fits the gradient at the
    CONSTANT init scores, unshrunk (averaging happens at export), with
    the per-iteration bagging masks providing the forest's resampling.

    ``real``: (n,) row-validity mask sharded over ``data`` (zeros on pad
    rows), folded into every iteration's mask.  ``bags``: (C, n) bagging
    masks sharded over ``data`` when ``bag_sharded``, else a constant
    (C, 1) broadcast — so a padded no-bagging fit costs one (n,) mask, not
    a (C, n) stack of identical copies.

    ``has_val``: validation rows ride the mesh too — ``val_bins`` is
    sharded over ``data`` with ALL features per shard (trees are
    replicated, so each shard scores its own validation slice), and the
    per-iteration validation margins come back as a (C, n_val) array for
    host-side metric replay / early stopping (the reference's executor-
    side eval, SURVEY.md §3.1).

    Returns (stacked replicated trees, sharded scores, sharded val_scores,
    per-iteration val history).
    """
    cfg = _sharded_cfg(mesh, cfg)

    def steps(bins, scores, labels, weights, real, bags, fis,
              val_bins, val_scores):
        binsT = bins.T   # fit-invariant; hoisted out of the scan

        def body(carry, xs):
            scores, val_scores = carry
            bag, fi = xs
            bag = jnp.broadcast_to(bag, scores.shape) * real
            g, h = obj.grad_hess(scores, labels, weights)
            gh = jnp.stack([g * bag, h * bag, bag], axis=1)
            # efb rides the closure: the (f, B)-sized maps replicate as
            # baked constants; per-feature expansion happens SHARD-LOCAL
            # before the psum (expansion is linear, so it commutes)
            tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg, efb,
                                             binsT=binsT)
            if not rf:
                scores = scores + lr * tree.leaf_value[row_leaf]
                tree = apply_shrinkage(tree, lr)
            if has_val:
                val_scores = val_scores + predict_tree_binned(
                    tree, val_bins, cfg.num_leaves)
                out_v = val_scores
            else:
                out_v = jnp.zeros((0,), jnp.float32)
            return (scores, val_scores), (tree, out_v)

        (scores, val_scores), (trees, val_hist) = jax.lax.scan(
            body, (scores, val_scores), (bags, fis))
        return trees, scores, val_scores, val_hist

    bag_spec = P(None, DATA_AXIS) if bag_sharded else P(None, None)
    val_hist_spec = P(None, DATA_AXIS) if has_val else P(None, None)
    fa = _f_ax(mesh)
    mapped = _shard_map(
        steps, mesh=mesh,
        in_specs=(P(DATA_AXIS, fa), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P(DATA_AXIS), bag_spec,
                  P(None, fa, None),
                  P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), val_hist_spec),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(1, 8))


def make_multiclass_scan(mesh: Mesh, obj: Objective, cfg: GrowerConfig,
                         lr: float, num_class: int, bag_sharded: bool,
                         has_val: bool = False, efb=None,
                         rf: bool = False):
    """Multiclass distributed chunk: grad/hess once per iteration for all K
    trees (LightGBM softmax semantics), K grow steps per scan iteration.
    Trees come back stacked (C*K, ...), iteration-major.

    ``rf``: random-forest mode — trees fit the gradient at the CONSTANT
    init scores, unshrunk (per-class averaging at export)."""
    cfg = _sharded_cfg(mesh, cfg)
    K = num_class

    def steps(bins, scores, labels, weights, real, bags, fis,
              val_bins, val_scores):
        binsT = bins.T   # fit-invariant; hoisted out of the scan

        def body(carry, xs):
            scores, val_scores = carry
            bag, fi = xs
            bag = jnp.broadcast_to(bag, (scores.shape[0],)) * real
            g, h = obj.grad_hess(scores, labels, weights)
            trees_k = []
            for k in range(K):
                gh = jnp.stack([g[:, k] * bag, h[:, k] * bag, bag], axis=1)
                tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg, efb,
                                                 binsT=binsT)
                if not rf:
                    scores = scores.at[:, k].add(
                        lr * tree.leaf_value[row_leaf])
                    tree = apply_shrinkage(tree, lr)
                if has_val:
                    val_scores = val_scores.at[:, k].add(
                        predict_tree_binned(tree, val_bins,
                                            cfg.num_leaves))
                trees_k.append(tree)
            trees = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *trees_k)
            out_v = val_scores if has_val else jnp.zeros((0, K), jnp.float32)
            return (scores, val_scores), (trees, out_v)

        (scores, val_scores), (trees, val_hist) = jax.lax.scan(
            body, (scores, val_scores), (bags, fis))
        trees = jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), trees)
        return trees, scores, val_scores, val_hist

    bag_spec = P(None, DATA_AXIS) if bag_sharded else P(None, None)
    val_hist_spec = P(None, DATA_AXIS, None) if has_val else P(None, None)
    fa = _f_ax(mesh)
    mapped = _shard_map(
        steps, mesh=mesh,
        in_specs=(P(DATA_AXIS, fa), P(DATA_AXIS, None),
                  P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), bag_spec,
                  P(None, fa, None),
                  P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=(P(), P(DATA_AXIS, None), P(DATA_AXIS, None),
                   val_hist_spec),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(1, 8))


def make_ranking_dart_step(mesh: Mesh, cfg: GrowerConfig, lr: float,
                           sigma: float, trunc: int):
    """One dart iteration for MESH LAMBDARANK: pairwise ΔNDCG gradients
    computed shard-local at the dropped-out scores (queries are packed
    per shard, so no collective touches the lambda tensors), tree grown
    data-parallel with psum histograms.  Host-side dropout bookkeeping is
    the shared ``_dart_host_loop``.  Data-only mesh (dropped-unit scoring
    reads whole feature rows)."""
    from .ranking import lambda_grad_sorted

    cfg = _sharded_cfg(mesh, cfg)

    def step(bins, binsT, s_minus, real, wmul, qidx, qmask, gains, labq,
             invmax, bag, fi):
        nl = s_minus.shape[0]
        g, h = lambda_grad_sorted(s_minus, qidx, qmask, gains, labq,
                                  invmax, sigma, trunc, nl)
        h = jnp.maximum(h, 1e-9)
        wb = wmul * bag
        gh = jnp.stack([g * wb, h * wb, real * bag], axis=1)
        tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg, binsT=binsT)
        tree = apply_shrinkage(tree, lr)
        return tree, tree.leaf_value[row_leaf]

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(None, DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS, None, None),
                  P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                  P(DATA_AXIS, None, None), P(DATA_AXIS, None),
                  P(DATA_AXIS), P(None, None)),
        out_specs=(P(), P(DATA_AXIS)),
        check_vma=False)
    return jax.jit(mapped)


def make_dart_step(mesh: Mesh, obj: Objective, cfg: GrowerConfig,
                   lr: float, num_class: int = 1):
    """One dart iteration over the mesh: fit a tree to the gradient at
    the dropped-out score vector ``s_minus`` (histogram psums over the
    ``data`` axis — and, on a 2-D mesh, feature-parallel split search —
    inside the grower), returning the replicated lr-shrunk tree and its
    data-sharded base contribution.  The host applies the 1/(k+1) dart
    normalization and tracks per-tree scales, exactly like the serial
    path — dropout bookkeeping is tiny host metadata, only the fit and
    the scoring ride the mesh."""
    cfg = _sharded_cfg(mesh, cfg)
    fshard = _feat_n(mesh) > 1
    K = num_class

    def step(bins, binsT, s_minus, labels, weights, bag, fi):
        g, h = obj.grad_hess(s_minus, labels, weights)
        if K == 1:
            gh = jnp.stack([g * bag, h * bag, bag], axis=1)
            tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg,
                                             binsT=binsT)
            tree = apply_shrinkage(tree, lr)
            return tree, tree.leaf_value[row_leaf]
        trees_k, bnews = [], []
        for k in range(K):
            gh = jnp.stack([g[:, k] * bag, h[:, k] * bag, bag], axis=1)
            tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg,
                                             binsT=binsT)
            tree = apply_shrinkage(tree, lr)
            trees_k.append(tree)
            bnews.append(tree.leaf_value[row_leaf])
        trees = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                       *trees_k)
        return trees, jnp.stack(bnews, axis=1)

    sc_spec = P(DATA_AXIS) if K == 1 else P(DATA_AXIS, None)
    bins_spec = (P(DATA_AXIS, FEATURE_AXIS) if fshard
                 else P(DATA_AXIS, None))
    binsT_spec = (P(FEATURE_AXIS, DATA_AXIS) if fshard
                  else P(None, DATA_AXIS))
    fi_spec = P(FEATURE_AXIS, None) if fshard else P(None, None)
    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=(bins_spec, binsT_spec, sc_spec,
                  P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  fi_spec),
        out_specs=(P(), sc_spec),
        check_vma=False)
    return jax.jit(mapped)


def make_tree_predict(mesh: Mesh, num_leaves: int, num_class: int = 1):
    """Replicated-tree scoring of mesh-sharded binned rows — dart's
    dropped-tree subtraction and validation scoring.  Data-only mesh:
    each shard walks its rows with all features local.  With a feature
    axis, the walk assembles each level's compare vector by psum
    (grower.predict_tree_binned_fshard — the scoring analog of the
    feature-parallel split-column broadcast).  ``num_class > 1`` scores
    one dart iteration's K stacked trees to (n, K)."""
    fshard = _feat_n(mesh) > 1
    if fshard:
        def walk(tree, bins):
            return predict_tree_binned_fshard(tree, bins, num_leaves,
                                              FEATURE_AXIS)
        bins_spec = P(DATA_AXIS, FEATURE_AXIS)
    else:
        def walk(tree, bins):
            return predict_tree_binned(tree, bins, num_leaves)
        bins_spec = P(DATA_AXIS, None)

    if num_class == 1:
        def pred(tree, bins):
            return walk(tree, bins)
        out_spec = P(DATA_AXIS)
    else:
        def pred(trees_st, bins):
            return jax.vmap(lambda t: walk(t, bins))(trees_st).T
        out_spec = P(DATA_AXIS, None)

    mapped = _shard_map(
        pred, mesh=mesh,
        in_specs=(P(), bins_spec),
        out_specs=out_spec,
        check_vma=False)
    return jax.jit(mapped)


def make_ranking_scan(mesh: Mesh, cfg: GrowerConfig, lr: float,
                      sigma: float, trunc: int, has_val: bool = False,
                      goss=None, bag_sharded: bool = False,
                      rf: bool = False):
    """Mesh-sharded lambdarank boosting (SURVEY.md §3.1 distributed
    lambdarank, BASELINE config MSLR): rows arrive query-packed per data
    shard (see :func:`mmlspark_tpu.gbdt.ranking.shard_queries`), so the
    pairwise ΔNDCG gradients are shard-LOCAL — no collective touches the
    (c, G, G) lambda tensors; only the histogram psum crosses ICI, exactly
    like the classifier path.

    ``qidx/qmask/gains/labq`` are (D*n_chunks, chunk, G) and ``invmax``
    (D*n_chunks, chunk), sharded over ``data`` on the leading axis;
    ``real`` masks pad rows.  Validation margins ride the mesh as in
    :func:`make_boost_scan`.

    ``goss``: optional ``(k1, k2, amp)`` — per-shard GOSS on top of the
    full lambdarank gradients: pairwise ΔNDCG gradients are computed on
    EVERY row (they need whole queries), then the tree grows on the
    top-|g·h| sample plus an amplified random remainder, exactly like
    distributed LightGBM's boosting=goss with a ranking objective.
    ``keys`` feeds the per-iteration PRNG (ignored otherwise).

    ``bags``: (C, n) bagging masks scattered through the query-pack
    permutation (constant (C, 1) when bagging is off); gradients and
    hessians are masked, membership (``real``) is not.  ``rf``: trees
    fit the gradients at the CONSTANT init scores, unshrunk (averaging
    at export) — random-forest mode with the ranking objective.
    """
    from .ranking import lambda_grad_sorted

    cfg = _sharded_cfg(mesh, cfg)

    def steps(bins, scores, real, wmul, qidx, qmask, gains, labq, invmax,
              keys, bags, fis, val_bins, val_scores):
        nl = scores.shape[0]
        binsT = bins.T   # fit-invariant; hoisted out of the scan

        def body(carry, xs):
            scores, val_scores = carry
            key, bag, fi = xs
            g, h = lambda_grad_sorted(scores, qidx, qmask, gains, labq,
                                      invmax, sigma, trunc, nl)
            h = jnp.maximum(h, 1e-9)
            # wmul = row weight * validity (LightGBM ranker weightCol
            # semantics); the count channel carries plain validity
            wb = wmul * jnp.broadcast_to(bag, (nl,))
            # count channel = validity * bag, matching the serial ranking
            # loop: with bagging the tree trains on the SAMPLE, so
            # min_data_in_leaf counts sampled rows (LightGBM semantics)
            cb = real * jnp.broadcast_to(bag, (nl,))
            if goss is None:
                gh = jnp.stack([g * wb, h * wb, cb], axis=1)
                tree, row_leaf = _grow_tree_impl(bins, gh, fi, cfg,
                                                 binsT=binsT)
                if not rf:
                    scores = scores + lr * tree.leaf_value[row_leaf]
            else:
                k1, k2, amp = goss
                if cfg.axis_name is not None:
                    key = jax.random.fold_in(
                        key, jax.lax.axis_index(cfg.axis_name))
                gm = g * wb
                hm = h * wb                       # pads carry wmul 0
                rank = jnp.argsort(-jnp.abs(gm * hm))
                top_idx = rank[:k1]
                rk = jax.random.uniform(key, (nl - k1,))
                other_idx = jnp.take(rank[k1:], jnp.argsort(rk)[:k2])
                idx = jnp.concatenate([top_idx, other_idx])
                amp_vec = jnp.concatenate([
                    jnp.ones(k1, jnp.float32),
                    jnp.full(k2, amp, jnp.float32)])
                gh = jnp.stack([jnp.take(gm, idx) * amp_vec,
                                jnp.take(hm, idx) * amp_vec,
                                jnp.take(real, idx)], axis=1)
                tree, _ = _grow_tree_impl(jnp.take(bins, idx, axis=0),
                                          gh, fi, cfg)
                scores = scores + lr * predict_tree_binned(
                    tree, bins, cfg.num_leaves)
            if not rf:
                tree = apply_shrinkage(tree, lr)
            if has_val:
                val_scores = val_scores + predict_tree_binned(
                    tree, val_bins, cfg.num_leaves)
                out_v = val_scores
            else:
                out_v = jnp.zeros((0,), jnp.float32)
            return (scores, val_scores), (tree, out_v)

        (scores, val_scores), (trees, val_hist) = jax.lax.scan(
            body, (scores, val_scores), (keys, bags, fis))
        return trees, scores, val_scores, val_hist

    val_hist_spec = P(None, DATA_AXIS) if has_val else P(None, None)
    bag_spec = P(None, DATA_AXIS) if bag_sharded else P(None, None)
    fa = _f_ax(mesh)
    mapped = _shard_map(
        steps, mesh=mesh,
        in_specs=(P(DATA_AXIS, fa), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P(DATA_AXIS, None, None),
                  P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                  P(DATA_AXIS, None, None), P(DATA_AXIS, None),
                  P(None, None), bag_spec,
                  P(None, fa, None),
                  P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), val_hist_spec),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(1, 13))


def prepare_arrays_from_shards(bins_shards, label_shards, weight_shards,
                               mesh: Mesh, num_class: int, init: float,
                               bin_dtype, shard_rows=None,
                               init_score_shards=None, _piece_spy=None):
    """Multi-host ingestion (SURVEY.md §7 hard part 4): assemble the global
    sharded training arrays from PER-SHARD inputs without materializing the
    full matrix on any single host.

    ``bins_shards[d]`` is data-shard d's binned rows (n_d, f) — in a real
    multi-host deployment the per-host Arrow reader output.  Shards are
    padded to the max shard length with zero-weight rows; every device
    piece is produced by ``jax.make_array_from_callback``, which asks only
    for the ADDRESSABLE devices' (S, f_shard) blocks, so peak host memory
    is this host's shards, not D of them.  On a multi-controller
    deployment pass ``None`` in the non-local slots of the three shard
    lists plus ``shard_rows`` (the global per-shard row counts, small
    metadata every host knows); the callback never touches non-local
    slots.  Returns the same tuple as :func:`prepare_arrays`
    (rp = total pad rows across shards).
    """
    D = int(mesh.shape[DATA_AXIS])
    fn = _feat_n(mesh)
    if len(bins_shards) != D:
        raise ValueError(
            f"need exactly one shard slot per data-mesh slice: got "
            f"{len(bins_shards)} slots for data={D}")
    from ..core.mesh import pad_to_multiple
    local = [d for d in range(D) if bins_shards[d] is not None]
    if not local:
        raise ValueError("no local shards (every slot is None)")
    f = bins_shards[local[0]].shape[1]
    for d in local:
        if bins_shards[d].shape[1] != f:
            raise ValueError(
                f"shard {d} has {bins_shards[d].shape[1]} features, "
                f"shard {local[0]} has {f}: all shards must agree")
        nl = len(label_shards[d])
        nw = len(weight_shards[d]) if weight_shards[d] is not None else nl
        if not (bins_shards[d].shape[0] == nl == nw):
            raise ValueError(
                f"shard {d}: bins rows {bins_shards[d].shape[0]}, labels "
                f"{nl}, weights {nw} must all match")
    f_padded = pad_to_multiple(f, fn)
    if shard_rows is not None:
        sizes = list(shard_rows)
        for d in local:
            if sizes[d] != bins_shards[d].shape[0]:
                raise ValueError(
                    f"shard_rows[{d}]={sizes[d]} does not match the local "
                    f"shard's {bins_shards[d].shape[0]} rows")
    elif len(local) == D:
        sizes = [b.shape[0] for b in bins_shards]
    else:
        raise ValueError("shard_rows is required when some shard slots "
                         "are None (multi-controller)")
    S = max(sizes)
    n_global = D * S

    def make(spec, dtype, fill, shard_source, width=None):
        sh = NamedSharding(mesh, spec)
        shape = (n_global,) if width is None else (n_global, width)

        def cb(index):
            r0, r1, _ = index[0].indices(n_global)
            d = r0 // S
            local = shard_source(d)
            rows = r1 - r0
            if width is None:
                out = np.full(rows, fill, dtype)
                r = min(local.shape[0], rows)
                out[:r] = local[:r]
            else:
                c0, c1s, _ = index[1].indices(width)
                out = np.full((rows, c1s - c0), fill, dtype)
                r = min(local.shape[0], rows)
                c1 = min(c1s, local.shape[1])
                if c1 > c0:
                    out[:r, :c1 - c0] = local[:r, c0:c1]
            if _piece_spy is not None:
                _piece_spy(out.shape)
            return out

        return jax.make_array_from_callback(shape, sh, cb)

    lab_dtype = np.int32 if num_class > 1 else np.float32
    bins_d = make(P(DATA_AXIS, _f_ax(mesh)), bin_dtype, 0,
                  lambda d: bins_shards[d], width=f_padded)
    lab_d = make(P(DATA_AXIS), lab_dtype, 0,
                 lambda d: np.asarray(label_shards[d], lab_dtype))
    w_d = make(P(DATA_AXIS), np.float32, 0.0,
               lambda d: np.asarray(weight_shards[d], np.float32))
    real_d = make(P(DATA_AXIS), np.float32, 0.0,
                  lambda d: np.ones(sizes[d], np.float32))
    # scores ride the callback path too — no transient global array on any
    # single device (the arrays this function exists to avoid); per-shard
    # init scores (initScoreCol) offset the local slice, pad rows keep the
    # plain init (their weight is zero anyway)
    def score_shard(d):
        if init_score_shards is None or init_score_shards[d] is None:
            base = np.full(sizes[d], init, np.float32)
        else:
            base = init + np.asarray(init_score_shards[d], np.float32)
        return base if num_class == 1 else \
            np.broadcast_to(base[:, None], (sizes[d], num_class))

    if num_class > 1:
        scores = make(P(DATA_AXIS, None), np.float32, init, score_shard,
                      width=num_class)
    else:
        scores = make(P(DATA_AXIS), np.float32, init, score_shard)
    rp = n_global - sum(sizes)
    return bins_d, lab_d, w_d, real_d, scores, rp, f_padded - f


def prepare_arrays(bins: np.ndarray, labels: np.ndarray, weights: np.ndarray,
                   mesh: Mesh, num_class: int, init: float,
                   init_scores: Optional[np.ndarray] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                              jnp.ndarray, jnp.ndarray, int, int]:
    """Pad rows/features to multiples of the mesh axes and device_put.

    Pad rows carry zero weight (excluded from histograms via the bag mask);
    pad features are constant bin 0 (never produce a valid split).
    """
    from ..core.mesh import pad_to_multiple
    n, f = bins.shape
    dn = int(mesh.shape[DATA_AXIS])
    fn = _feat_n(mesh)
    rp = pad_to_multiple(n, dn) - n
    fp = pad_to_multiple(f, fn) - f
    if rp:
        bins = np.concatenate(
            [bins, np.zeros((rp, bins.shape[1]), bins.dtype)], axis=0)
        labels = np.concatenate([labels, np.zeros(rp, labels.dtype)])
        weights = np.concatenate([weights, np.zeros(rp, weights.dtype)])
    if fp:
        bins = np.concatenate(
            [bins, np.zeros((bins.shape[0], fp), bins.dtype)], axis=1)
    real = np.concatenate(
        [np.ones(n, np.float32), np.zeros(rp, np.float32)])

    bins_d = jax.device_put(
        jnp.asarray(bins),   # dtype preserved (uint8 when B <= 256)
        NamedSharding(mesh, P(DATA_AXIS, _f_ax(mesh))))
    lab_d = jax.device_put(
        jnp.asarray(labels, jnp.int32 if num_class > 1 else jnp.float32),
        NamedSharding(mesh, P(DATA_AXIS)))
    w_d = jax.device_put(jnp.asarray(weights, jnp.float32),
                         NamedSharding(mesh, P(DATA_AXIS)))
    real_d = jax.device_put(jnp.asarray(real),
                            NamedSharding(mesh, P(DATA_AXIS)))
    shape = (bins.shape[0], num_class) if num_class > 1 else (bins.shape[0],)
    spec = P(DATA_AXIS, None) if num_class > 1 else P(DATA_AXIS)
    scores0 = np.full(shape, init, np.float32)
    if init_scores is not None:
        pad_init = np.concatenate(
            [np.asarray(init_scores, np.float32),
             np.zeros((rp,) + init_scores.shape[1:], np.float32)])
        scores0 = scores0 + (pad_init if scores0.ndim == pad_init.ndim
                             else pad_init[:, None])
    scores = jax.device_put(jnp.asarray(scores0),
                            NamedSharding(mesh, spec))
    return bins_d, lab_d, w_d, real_d, scores, rp, fp
