"""LightGBMRanker: lambdarank objective and estimator.

TPU-native re-implementation of the reference's ranker
(lightgbm/LightGBMRanker.scala, expected path, UNVERIFIED; SURVEY.md §2.1)
whose native engine computes pairwise ΔNDCG-weighted gradients per query.

Static-shape design (SURVEY.md §7 hard part 6): rows are sorted by query on
the host and packed into a padded ``(num_queries, max_group)`` index matrix;
the jitted gradient function scans over query *chunks*, computing the full
``(chunk, G, G)`` pairwise lambda tensor per chunk — bucketed padding instead
of LightGBM's per-query loops.  Semantics follow lambdarank:

* gains ``2^label - 1``, discounts ``1/log2(2 + rank)`` with ranks from the
  *current* scores, ΔNDCG normalized by the query's ideal DCG;
* ``lambda = -sigma * p_ij * ΔNDCG``, ``hess = sigma^2 p (1-p) ΔNDCG``;
* pairs participate when either member ranks above the truncation level
  (LightGBM's lambdarank_truncation_level).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import Param, TypeConverters
from ..core.schema import DataTable, features_matrix
from .base import LightGBMBase, LightGBMModelBase
from .booster import Booster


def pack_queries(query_ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                                 np.ndarray]:
    """Group rows by query.

    Returns (order, qidx, qmask): ``order`` sorts rows by query (stable);
    ``qidx`` is (Q, G) of positions into the *sorted* row order (0 padded);
    ``qmask`` marks real entries.
    """
    order = np.argsort(query_ids, kind="stable")
    sorted_q = query_ids[order]
    _, starts, counts = np.unique(sorted_q, return_index=True,
                                  return_counts=True)
    Q, G = len(starts), int(counts.max())
    qidx = np.zeros((Q, G), np.int32)
    qmask = np.zeros((Q, G), np.float32)
    for i, (s, c) in enumerate(zip(starts, counts)):
        qidx[i, :c] = np.arange(s, s + c)
        qmask[i, :c] = 1.0
    return order.astype(np.int32), qidx, qmask


def _dcg_discount(rank):
    return 1.0 / jnp.log2(2.0 + rank)


def query_tensors(labels_sorted: np.ndarray, qidx: np.ndarray,
                  qmask: np.ndarray, truncation_level: int,
                  max_label: int = 31):
    """Host-side static per-query tensors: gains, padded labels, and the
    inverse ideal DCG (shared by the serial and mesh-sharded lambdarank
    paths)."""
    Q, G = qidx.shape
    gains_row = (2.0 ** np.minimum(labels_sorted, max_label) - 1.0)
    lab_q = labels_sorted[qidx] * qmask - (1.0 - qmask)   # pad -> -1
    gains_q = gains_row[qidx] * qmask
    ideal = -np.sort(-gains_q, axis=1)
    k = min(truncation_level, G)
    disc = 1.0 / np.log2(2.0 + np.arange(G))
    max_dcg = (ideal[:, :k] * disc[:k]).sum(axis=1)
    inv_max_dcg = np.where(max_dcg > 0,
                           1.0 / np.maximum(max_dcg, 1e-12), 0.0)
    return (gains_q.astype(np.float32), lab_q.astype(np.float32),
            inv_max_dcg.astype(np.float32))


def lambda_grad_sorted(s_sorted, qidx_c, qmask_c, gains_c, labq_c, invmax_c,
                       sigma: float, trunc: int, n: int):
    """(n,) lambdarank grad/hess for scores already sorted by query.

    Query tensors arrive pre-chunked ``(n_chunks, c, G)``; a ``lax.scan``
    over chunks bounds the transient (c, G, G) pairwise tensors.  Pure
    function of jax arrays — usable inside shard_map (each shard passes
    its LOCAL query structures and local sorted scores)."""
    sig, tr = float(sigma), int(trunc)

    def chunk_step(carry, args):
        g_acc, h_acc = carry
        qi, qm, gains, labs, invmax = args         # (c, G, ...)
        s = s_sorted[qi] * qm - 1e9 * (1.0 - qm)   # pad to -inf-ish
        # ranks within query from current scores (descending)
        rank_order = jnp.argsort(-s, axis=1)
        ranks = jnp.argsort(rank_order, axis=1).astype(jnp.float32)
        disc = _dcg_discount(ranks)                # (c, G)
        # pairwise tensors (c, G, G): i vs j
        better = (labs[:, :, None] > labs[:, None, :])
        in_trunc = (ranks[:, :, None] < tr) | (ranks[:, None, :] < tr)
        pair_mask = (better & in_trunc).astype(jnp.float32) * \
            qm[:, :, None] * qm[:, None, :]
        dgain = jnp.abs(gains[:, :, None] - gains[:, None, :])
        ddisc = jnp.abs(disc[:, :, None] - disc[:, None, :])
        delta = dgain * ddisc * invmax[:, None, None]
        sdiff = s[:, :, None] - s[:, None, :]
        p = jax.nn.sigmoid(-sig * sdiff)           # P(j beats i)
        lam = -sig * p * delta * pair_mask         # grad for i (winner)
        hes = sig * sig * p * (1.0 - p) * delta * pair_mask
        g_q = jnp.sum(lam, axis=2) - jnp.sum(lam, axis=1)
        h_q = jnp.sum(hes, axis=2) + jnp.sum(hes, axis=1)
        # scatter back into sorted row order (pad slots -> dropped)
        flat_qi = jnp.where(qm > 0, qi.astype(jnp.int32), n).reshape(-1)
        g_acc = g_acc.at[flat_qi].add((g_q * qm).reshape(-1), mode="drop")
        h_acc = h_acc.at[flat_qi].add((h_q * qm).reshape(-1), mode="drop")
        return (g_acc, h_acc), None

    init = (jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32))
    (g_s, h_s), _ = jax.lax.scan(
        chunk_step, init, (qidx_c, qmask_c, gains_c, labq_c, invmax_c))
    return g_s, h_s


def make_lambdarank_grad_fn(labels: np.ndarray, query_ids: np.ndarray,
                            sigma: float = 1.0,
                            truncation_level: int = 30,
                            max_label: int = 31,
                            query_chunk_pairs: int = 4_000_000,
                            weights: Optional[np.ndarray] = None):
    """Build ``fn(scores) -> (grad, hess)`` closed over the query structure.

    ``scores`` is in original row order (n,); so are the returned grad/hess.
    ``weights`` are per-row multipliers applied to grad/hess (LightGBM
    lambdarank weight semantics).
    """
    n = len(labels)
    order, qidx, qmask = pack_queries(np.asarray(query_ids))
    Q, G = qidx.shape
    chunk = max(1, min(Q, query_chunk_pairs // max(G * G, 1)))
    pad_q = (-Q) % chunk
    if pad_q:
        qidx = np.concatenate([qidx, np.zeros((pad_q, G), np.int32)])
        qmask = np.concatenate([qmask, np.zeros((pad_q, G), np.float32)])

    labels_sorted = np.asarray(labels, np.float32)[order]
    gains_q, lab_q, inv_max_dcg = query_tensors(
        labels_sorted, qidx[:Q], qmask[:Q], truncation_level, max_label)
    if pad_q:
        gains_q = np.concatenate([gains_q, np.zeros((pad_q, G), np.float32)])
        lab_q = np.concatenate([lab_q, -np.ones((pad_q, G), np.float32)])
        inv_max_dcg = np.concatenate([inv_max_dcg,
                                      np.zeros(pad_q, np.float32)])

    qidx_d = jnp.asarray(qidx.reshape(-1, chunk, G))
    qmask_d = jnp.asarray(qmask.reshape(-1, chunk, G))
    gains_d = jnp.asarray(gains_q.reshape(-1, chunk, G))
    labq_d = jnp.asarray(lab_q.reshape(-1, chunk, G))
    invmax_d = jnp.asarray(inv_max_dcg.reshape(-1, chunk))
    order_d = jnp.asarray(order)
    w_d = None if weights is None else jnp.asarray(weights, jnp.float32)
    sig = float(sigma)
    trunc = int(truncation_level)

    @jax.jit
    def grad_fn(scores):
        s_sorted = scores[order_d]                     # (n,) sorted by query
        g_s, h_s = lambda_grad_sorted(
            s_sorted, qidx_d, qmask_d, gains_d, labq_d, invmax_d,
            sig, trunc, n)
        # back to original row order
        g = jnp.zeros(n, jnp.float32).at[order_d].set(g_s)
        h = jnp.zeros(n, jnp.float32).at[order_d].set(h_s)
        if w_d is not None:
            g = g * w_d
            h = h * w_d
        return g, jnp.maximum(h, 1e-9)

    return grad_fn


def shard_queries(labels: np.ndarray, query_ids: np.ndarray, n_shards: int,
                  truncation_level: int, max_label: int = 31,
                  query_chunk_pairs: int = 4_000_000, assign=None):
    """Partition whole queries across data shards (greedy row balancing).

    The mesh-sharded lambdarank layout (SURVEY.md §3.1 distributed
    lambdarank): rows are physically regrouped so every query lives on
    exactly ONE data shard; the pairwise gradient then needs no cross-
    shard communication, and tree growth stays plain data-parallel psum.

    Returns ``(perm, real, qt)``: ``perm`` (D*S,) maps packed slot → source
    row (-1 pad), ``real`` the 0/1 validity mask, and ``qt`` the per-shard
    chunked query tensors (qidx, qmask, gains, labq, invmax) with shapes
    (D*n_chunks, chunk, G)/(D*n_chunks, chunk) ready for a
    ``P('data', ...)`` sharding — each shard's qidx indexes its LOCAL
    packed rows.

    ``assign`` (optional) overrides the greedy balancer with a fixed
    query → shard map, one entry per unique query id in SORTED id order —
    the sharded-ingestion path pins each query to the shard whose host
    already holds its rows (see :func:`shard_queries_from_shards`).
    """
    q = np.asarray(query_ids)
    order = np.argsort(q, kind="stable")
    sorted_q = q[order]
    _, starts, counts = np.unique(sorted_q, return_index=True,
                                  return_counts=True)
    D = n_shards
    loads = np.zeros(D, np.int64)
    if assign is None:
        assign = np.empty(len(starts), np.int32)
        for i, c in enumerate(counts):   # greedy: least-loaded shard
            s = int(np.argmin(loads))
            assign[i] = s
            loads[s] += c
    else:
        assign = np.asarray(assign, np.int32)
        if len(assign) != len(starts):
            raise ValueError(
                f"assign has {len(assign)} entries for {len(starts)} "
                "unique queries")
        np.add.at(loads, assign, counts)
    S = int(loads.max())
    G = int(counts.max())
    qs_per_shard = np.bincount(assign, minlength=D)
    Qs = int(qs_per_shard.max()) if len(starts) else 1
    chunk = max(1, min(Qs, query_chunk_pairs // max(G * G, 1)))
    Qp = Qs + ((-Qs) % chunk)

    perm = np.full((D, S), -1, np.int64)
    qidx = np.zeros((D, Qp, G), np.int32)
    qmask = np.zeros((D, Qp, G), np.float32)
    gains = np.zeros((D, Qp, G), np.float32)
    labq = -np.ones((D, Qp, G), np.float32)
    invmax = np.zeros((D, Qp), np.float32)

    labels_sorted = np.asarray(labels, np.float32)[order]
    fill_rows = np.zeros(D, np.int64)
    fill_q = np.zeros(D, np.int64)
    for i, (st, c) in enumerate(zip(starts, counts)):
        d = assign[i]
        r0 = fill_rows[d]
        perm[d, r0:r0 + c] = order[st:st + c]
        qi = fill_q[d]
        qidx[d, qi, :c] = np.arange(r0, r0 + c)
        qmask[d, qi, :c] = 1.0
        g_q, l_q, im = query_tensors(
            labels_sorted[st:st + c],
            np.arange(c, dtype=np.int32)[None, :c],
            np.ones((1, c), np.float32), truncation_level, max_label)
        gains[d, qi, :c] = g_q[0]
        labq[d, qi, :c] = l_q[0]
        invmax[d, qi] = im[0]
        fill_rows[d] += c
        fill_q[d] += 1

    real = (perm >= 0).astype(np.float32).reshape(-1)
    qt = (qidx.reshape(D * (Qp // chunk), chunk, G),
          qmask.reshape(D * (Qp // chunk), chunk, G),
          gains.reshape(D * (Qp // chunk), chunk, G),
          labq.reshape(D * (Qp // chunk), chunk, G),
          invmax.reshape(D * (Qp // chunk), chunk))
    return perm.reshape(-1), real, qt


def shard_queries_from_shards(label_shards, qid_shards, truncation_level: int,
                              max_label: int = 31,
                              query_chunk_pairs: int = 4_000_000):
    """Query packing for SHARDED ingestion: each query stays on the shard
    whose host already holds its rows — no cross-host row movement, the
    multi-host MSLR contract (SURVEY.md §7 hard part 4: per-host readers
    deliver whole queries; the reference's distributed lambdarank likewise
    requires group-contiguous partitions).

    ``label_shards`` / ``qid_shards`` are the per-shard 1-D lists (complete
    on every controller — small metadata, like the plain sharded path's
    label lists).  A query whose id appears in two shards is an ingestion
    error and raises.

    Returns ``(perm, real, qt, offsets)``: the same global packing triple
    as :func:`shard_queries` (``perm`` in shard-concatenation row order)
    plus the per-shard row offsets, so callers can translate packed slots
    to LOCAL shard rows: ``local = perm[d*S + j] - offsets[d]``.
    """
    D = len(qid_shards)
    sizes = np.array([len(np.asarray(q)) for q in qid_shards], np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    qids = np.concatenate([np.asarray(q) for q in qid_shards])
    labels = np.concatenate([np.asarray(l, np.float32)
                             for l in label_shards])
    if len(labels) != len(qids):
        raise ValueError(
            f"labels ({len(labels)}) and query ids ({len(qids)}) differ")
    shard_of_row = np.repeat(np.arange(D, dtype=np.int32), sizes)
    uq, inv = np.unique(qids, return_inverse=True)
    lo = np.full(len(uq), D, np.int32)
    hi = np.full(len(uq), -1, np.int32)
    np.minimum.at(lo, inv, shard_of_row)
    np.maximum.at(hi, inv, shard_of_row)
    spans = np.nonzero(lo != hi)[0]
    if len(spans):
        bad = uq[spans[0]]
        raise ValueError(
            f"query {bad!r} spans shards {lo[spans[0]]} and "
            f"{hi[spans[0]]}: sharded lambdarank requires every query's "
            "rows on ONE shard (group-contiguous ingestion)")
    perm, real, qt = shard_queries(
        labels, qids, D, truncation_level, max_label=max_label,
        query_chunk_pairs=query_chunk_pairs, assign=lo)
    return perm, real, qt, offsets


class LightGBMRanker(LightGBMBase):
    """lambdarank estimator; mirrors the reference's LightGBMRanker API."""

    _default_objective = "lambdarank"

    groupCol = Param("groupCol", "Column with the query/group id",
                     default="query", typeConverter=TypeConverters.toString)
    maxPosition = Param("maxPosition", "NDCG truncation level", default=30,
                        typeConverter=TypeConverters.toInt)
    sigma = Param("sigma", "Sigmoid scaling of pairwise logistic loss",
                  default=1.0, typeConverter=TypeConverters.toFloat)
    evalAt = Param("evalAt", "NDCG@k positions for evaluation",
                   default=[1, 3, 5, 10],
                   typeConverter=TypeConverters.toListInt)

    def __init__(self, **kwargs):
        kwargs.setdefault("objective", "lambdarank")
        super().__init__(**kwargs)

    def _grad_fn_override(self, table: DataTable, train_idx, y, w):
        q = np.asarray(table[self.getGroupCol()])[train_idx]
        return make_lambdarank_grad_fn(
            y, q, sigma=self.getSigma(),
            truncation_level=self.getMaxPosition(), weights=w)

    def _ranking_info(self, table: DataTable, train_idx):
        return {
            "query_ids": np.asarray(table[self.getGroupCol()])[train_idx],
            "sigma": self.getSigma(),
            "truncation_level": self.getMaxPosition(),
        }

    def _val_metric_fn(self, table: DataTable, val_mask):
        if val_mask is None or not val_mask.any():
            return None
        q_val = np.asarray(table[self.getGroupCol()])[val_mask]
        k = max(self.getEvalAt())

        def neg_ndcg(scores, labels, weights):
            return -ndcg_at_k(np.asarray(scores), np.asarray(labels),
                              q_val, k=k)
        return neg_ndcg

    def _make_model(self, booster: Booster) -> "LightGBMRankerModel":
        return LightGBMRankerModel(booster=booster)


class LightGBMRankerModel(LightGBMModelBase):

    def _transform(self, table: DataTable) -> DataTable:
        X = features_matrix(table, self.getFeaturesCol())
        pred = np.asarray(self._booster.predict_margin(X))
        out = self._with_shap(table, X)
        return out.withColumn(self.getPredictionCol(),
                              pred.astype(np.float64))


def ndcg_at_k(scores: np.ndarray, labels: np.ndarray, query_ids: np.ndarray,
              k: int = 10) -> float:
    """Mean NDCG@k across queries (evaluation helper, numpy)."""
    out, cnt = 0.0, 0
    for q in np.unique(query_ids):
        m = query_ids == q
        s, l = scores[m], labels[m]
        if len(l) < 2 or l.max() == l.min():
            continue
        order = np.argsort(-s)
        gains = 2.0 ** l - 1
        disc = 1.0 / np.log2(2 + np.arange(len(l)))
        dcg = (gains[order][:k] * disc[:k]).sum()
        idcg = (np.sort(gains)[::-1][:k] * disc[:k]).sum()
        if idcg > 0:
            out += dcg / idcg
            cnt += 1
    return out / max(cnt, 1)
