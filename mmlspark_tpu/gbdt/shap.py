"""TreeSHAP feature contributions (Lundberg et al. 2018, Algorithm 2).

The reference exposes per-row SHAP values through
``featuresShapCol`` / LightGBM's ``predict(..., pred_contrib=True)``
(lightgbm/LightGBMClassifier.scala featuresShapCol, expected path,
UNVERIFIED — SURVEY.md §2.1).  This is the exact path-dependent TreeSHAP
over the exported :class:`HostTree` forest: per tree, a recursive walk
maintains the "unique path" of features with their zero/one fractions and
Shapley permutation weights; contributions satisfy local accuracy
(``sum(phi) + expected == margin``), which the test suite asserts
row-for-row.

Host-side numpy: explanation workloads are small batches, and the
recursion is over tree *paths* (depth ≤ 31 here), not rows x leaves.
"""

from __future__ import annotations

from typing import List

import numpy as np


def _thr32_up(threshold: np.ndarray) -> np.ndarray:
    """Thresholds rounded UP to float32 — the predictor's convention
    (booster._stack's thr32), so the f32 decision agrees with the exact
    f64 threshold for every f32-representable x."""
    v = threshold.astype(np.float32)
    low = v.astype(np.float64) < threshold
    v[low] = np.nextafter(v[low], np.float32(np.inf))
    return v


def _decide_left(tree, thr32: np.ndarray, node: int,
                 xrow: np.ndarray) -> bool:
    """Mirror _predict_forest's decision EXACTLY (f32 inputs vs up-rounded
    f32 thresholds; numeric NaN always right; categorical NaN by the
    missing_left bit; unseen/out-of-range categories right) — any
    divergence breaks SHAP local accuracy on those rows."""
    f = int(tree.split_feature[node])
    v = xrow[f]                                    # float32
    dt = int(tree.decision_type[node])
    if dt & 1:                                     # categorical bitset
        if np.isnan(v):
            return bool(dt & 2)                    # missing_left bit
        c = int(v)
        j = int(tree.threshold[node])
        w0, w1 = tree.cat_boundaries[j], tree.cat_boundaries[j + 1]
        words = tree.cat_threshold[w0:w1]
        if c < 0 or (c >> 5) >= len(words):
            return False                           # unseen -> right
        return bool((int(words[c >> 5]) >> (c & 31)) & 1)
    if np.isnan(v):
        return False                               # numeric NaN -> right
    return v <= thr32[node]


def _subtree_stats(tree):
    """(expected value, cover) per signed node id: count-weighted mean of
    leaf values below each node — LightGBM's ``Tree::ExpectedValue``."""
    m = len(tree.split_feature)
    exp_internal = np.zeros(m, np.float64)
    cov_internal = np.zeros(m, np.float64)

    def rec(node: int):
        if node < 0:
            leaf = ~node
            return (float(tree.leaf_value[leaf]),
                    float(max(tree.leaf_count[leaf], 1)))
        vl, cl = rec(int(tree.left_child[node]))
        vr, cr = rec(int(tree.right_child[node]))
        c = cl + cr
        v = (vl * cl + vr * cr) / c
        exp_internal[node] = v
        cov_internal[node] = c
        return v, c

    if m:
        rec(0)
    return exp_internal, cov_internal


class _Path:
    """The unique path: parallel arrays of feature index d, zero fraction
    z, one fraction o, and permutation weight w."""
    __slots__ = ("d", "z", "o", "w", "n")

    def __init__(self, cap: int):
        self.d = np.full(cap, -2, np.int64)
        self.z = np.zeros(cap, np.float64)
        self.o = np.zeros(cap, np.float64)
        self.w = np.zeros(cap, np.float64)
        self.n = 0

    def copy(self) -> "_Path":
        p = _Path(len(self.d))
        p.d[:] = self.d
        p.z[:] = self.z
        p.o[:] = self.o
        p.w[:] = self.w
        p.n = self.n
        return p


def _extend(p: _Path, pz: float, po: float, pi: int) -> None:
    i = p.n
    p.d[i], p.z[i], p.o[i] = pi, pz, po
    p.w[i] = 1.0 if i == 0 else 0.0
    for j in range(i - 1, -1, -1):
        p.w[j + 1] += po * p.w[j] * (j + 1) / (i + 1)
        p.w[j] = pz * p.w[j] * (i - j) / (i + 1)
    p.n = i + 1


def _unwind(p: _Path, i: int) -> None:
    l = p.n - 1
    o, z = p.o[i], p.z[i]
    n = p.w[l]
    for j in range(l - 1, -1, -1):
        if o != 0:
            t = p.w[j]
            p.w[j] = n * (l + 1) / ((j + 1) * o)
            n = t - p.w[j] * z * (l - j) / (l + 1)
        else:
            p.w[j] = p.w[j] * (l + 1) / (z * (l - j))
    for j in range(i, l):
        p.d[j], p.z[j], p.o[j] = p.d[j + 1], p.z[j + 1], p.o[j + 1]
    p.n = l


def _unwound_sum(p: _Path, i: int) -> float:
    l = p.n - 1
    o, z = p.o[i], p.z[i]
    total = 0.0
    n = p.w[l]
    for j in range(l - 1, -1, -1):
        if o != 0:
            t = n * (l + 1) / ((j + 1) * o)
            total += t
            n = p.w[j] - t * z * (l - j) / (l + 1)
        else:
            total += p.w[j] * (l + 1) / (z * (l - j))
    return total


class _TreePrep:
    """Row-independent per-tree precomputation, hoisted out of the row
    loop: expected values/covers per node, up-rounded f32 thresholds, and
    the path capacity."""
    __slots__ = ("exp_v", "cov", "thr32", "cap")

    def __init__(self, tree):
        self.exp_v, self.cov = _subtree_stats(tree)
        self.thr32 = _thr32_up(tree.threshold)
        self.cap = tree.max_depth() + 2


def tree_contribs(tree, prep: _TreePrep, xrow: np.ndarray,
                  phi: np.ndarray) -> None:
    """Accumulate one tree's SHAP contributions for one row into ``phi``
    (length f+1; the trailing slot takes the tree's expected value)."""
    if tree.num_leaves <= 1:
        phi[-1] += float(tree.leaf_value[0])
        return
    phi[-1] += prep.exp_v[0]

    def value(node: int) -> float:
        return (float(tree.leaf_value[~node]) if node < 0
                else prep.exp_v[node])

    def cover(node: int) -> float:
        return (float(max(tree.leaf_count[~node], 1)) if node < 0
                else prep.cov[node])

    def rec(node: int, path: _Path, pz: float, po: float, pi: int) -> None:
        path = path.copy()
        _extend(path, pz, po, pi)
        if node < 0:
            for i in range(1, path.n):
                w = _unwound_sum(path, i)
                phi[path.d[i]] += w * (path.o[i] - path.z[i]) * value(node)
            return
        f = int(tree.split_feature[node])
        left = _decide_left(tree, prep.thr32, node, xrow)
        hot = int(tree.left_child[node] if left else tree.right_child[node])
        cold = int(tree.right_child[node] if left
                   else tree.left_child[node])
        iz = io = 1.0
        k = -1
        for i in range(1, path.n):
            if path.d[i] == f:
                k = i
                break
        if k >= 0:
            iz, io = path.z[k], path.o[k]
            _unwind(path, k)
        c = cover(node)
        rec(hot, path, iz * cover(hot) / c, io, f)
        rec(cold, path, iz * cover(cold) / c, 0.0, f)

    rec(0, _Path(prep.cap), 1.0, 1.0, -1)


def predict_contrib(booster, X: np.ndarray) -> np.ndarray:
    """(n, K*(f+1)) SHAP contributions: per class, one value per feature
    plus the expected-value slot last (LightGBM pred_contrib layout).

    Inputs are cast to float32 like the jitted predictor, so the SHAP
    walk and the prediction walk take identical paths on every row.
    """
    X = np.asarray(X, np.float32)
    n = X.shape[0]
    f = booster.max_feature_idx + 1
    K = booster.num_class
    out = np.zeros((n, K, f + 1), np.float64)
    for t_idx, tree in enumerate(booster.trees):
        k = t_idx % K
        prep = _TreePrep(tree)
        for r in range(n):
            tree_contribs(tree, prep, X[r], out[r, k])
    if booster.init_score:
        out[:, :, -1] += booster.init_score
    return out.reshape(n, K * (f + 1))
