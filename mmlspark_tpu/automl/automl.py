"""FindBestModel / TuneHyperparameters / HyperparamBuilder.

Reference: automl/*.scala (expected paths, UNVERIFIED — SURVEY.md §2.1).
Task-parallel candidate evaluation (SURVEY.md §2.3 "task parallelism") maps
to a thread pool here: each candidate fit is itself jax-jitted compute, so
threads overlap host-side orchestration while XLA serializes device work.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.params import HasLabelCol, Param, TypeConverters, HasSeed
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import DataTable
from ..core import serialize
from ..train.metrics import ComputeModelStatistics

_MAXIMIZE = {"AUC", "accuracy", "precision", "recall", "R^2"}
_METRIC_COL = {"auc": "AUC", "accuracy": "accuracy",
               "precision": "precision", "recall": "recall",
               "mse": "mean_squared_error",
               "rmse": "root_mean_squared_error",
               "mae": "mean_absolute_error", "r2": "R^2"}


def _evaluate(model: Transformer, table: DataTable, metric: str,
              labelCol: str) -> float:
    scored = model._transform(table)
    kind = ("classification"
            if _METRIC_COL[metric] in ("AUC", "accuracy", "precision",
                                       "recall") else "regression")
    stats = ComputeModelStatistics(
        evaluationMetric=kind, labelCol=labelCol)._transform(scored)
    return float(stats[_METRIC_COL[metric]][0])


class _EvalParams(HasLabelCol):
    evaluationMetric = Param("evaluationMetric",
                             "Metric to optimize: auc|accuracy|precision|"
                             "recall|mse|rmse|mae|r2",
                             default="auc",
                             typeConverter=TypeConverters.toString,
                             validator=lambda v: v in _METRIC_COL)


class FindBestModel(_EvalParams, Estimator):
    """Fits/evaluates candidate models and keeps the best
    (automl/FindBestModel.scala)."""

    def __init__(self, models: Optional[Sequence[Estimator]] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self._models = list(models or [])

    def setModels(self, models: Sequence[Estimator]) -> "FindBestModel":
        self._models = list(models)
        return self

    def getModels(self) -> List[Estimator]:
        return list(self._models)

    def _save_extra(self, path: str) -> None:
        serialize.save_stage_list(self._models, os.path.join(path, "models"))

    def _load_extra(self, path: str) -> None:
        p = os.path.join(path, "models")
        self._models = serialize.load_stage_list(p) if os.path.exists(p) \
            else []

    def _fit(self, table: DataTable) -> "BestModel":
        if not self._models:
            raise ValueError("FindBestModel needs candidate models")
        metric = self.getEvaluationMetric()
        maximize = _METRIC_COL[metric] in _MAXIMIZE
        rows: List[Dict[str, Any]] = []
        best_val, best_fitted = None, None
        for est in self._models:
            fitted = est._fit(table) if isinstance(est, Estimator) else est
            val = _evaluate(fitted, table, metric, self.getLabelCol())
            rows.append({"model": type(est).__name__, metric: val})
            better = (not np.isnan(val)
                      and (best_val is None
                           or (val > best_val if maximize else val < best_val)))
            if better:
                best_val, best_fitted = val, fitted
        if best_fitted is None:
            raise ValueError(
                "Every candidate produced a NaN metric; check the "
                "evaluation data")
        model = BestModel(fitted=best_fitted, metric_value=best_val,
                          all_results=rows)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class BestModel(_EvalParams, Model):
    def __init__(self, fitted: Optional[Transformer] = None,
                 metric_value: Optional[float] = None,
                 all_results: Optional[List[Dict[str, Any]]] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self._fitted = fitted
        self._metric_value = metric_value
        self._all_results = list(all_results or [])

    def getBestModel(self) -> Transformer:
        return self._fitted

    def getBestModelMetrics(self) -> Optional[float]:
        return self._metric_value

    def getAllModelMetrics(self) -> List[Dict[str, Any]]:
        return list(self._all_results)

    def _transform(self, table: DataTable) -> DataTable:
        return self._fitted._transform(table)

    def _save_extra(self, path: str) -> None:
        serialize.save_stage(self._fitted, os.path.join(path, "best"),
                             overwrite=True)
        serialize.save_json(path, "results", {
            "metric_value": self._metric_value,
            "all_results": self._all_results})

    def _load_extra(self, path: str) -> None:
        self._fitted = serialize.load_stage(os.path.join(path, "best"))
        info = serialize.load_json(path, "results")
        self._metric_value = info["metric_value"]
        self._all_results = info["all_results"]


# -- hyperparameter spaces ----------------------------------------------------

class DiscreteHyperParam:
    """A finite set of values (automl/HyperparamBuilder.scala)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng: np.random.Generator) -> Any:
        return self.values[int(rng.integers(len(self.values)))]

    def grid(self) -> List[Any]:
        return list(self.values)


class RangeHyperParam:
    """A [lo, hi) range, float or int (automl/HyperparamBuilder.scala)."""

    def __init__(self, lo, hi, isInt: Optional[bool] = None):
        self.lo, self.hi = lo, hi
        self.isInt = (isinstance(lo, (int, np.integer))
                      and isinstance(hi, (int, np.integer))
                      if isInt is None else isInt)

    def sample(self, rng: np.random.Generator) -> Any:
        if self.isInt:
            return int(rng.integers(self.lo, self.hi))
        return float(rng.uniform(self.lo, self.hi))

    def grid(self, n: int = 5) -> List[Any]:
        if self.isInt:
            vals = np.unique(np.linspace(
                self.lo, max(self.lo, self.hi - 1), n).astype(int))
            return [int(v) for v in vals]
        return [float(v) for v in np.linspace(self.lo, self.hi, n)]


def _space_to_json(space) -> Dict[str, Any]:
    if isinstance(space, (list, tuple)):  # GridSpace accepts raw sequences
        return {"type": "discrete", "values": list(space)}
    if isinstance(space, DiscreteHyperParam):
        return {"type": "discrete", "values": space.values}
    if isinstance(space, RangeHyperParam):
        return {"type": "range", "lo": space.lo, "hi": space.hi,
                "isInt": space.isInt}
    raise TypeError(f"Cannot serialize hyperparam space {type(space)}")


def _space_from_json(obj: Dict[str, Any]):
    if obj["type"] == "discrete":
        return DiscreteHyperParam(obj["values"])
    return RangeHyperParam(obj["lo"], obj["hi"], isInt=obj["isInt"])


class HyperparamBuilder:
    """Collects (paramName → space) pairs."""

    def __init__(self):
        self._spaces: Dict[str, Any] = {}

    def addHyperparam(self, name: str, space) -> "HyperparamBuilder":
        self._spaces[name] = space
        return self

    def build(self) -> Dict[str, Any]:
        return dict(self._spaces)


class RandomSpace:
    """Random sampling over a space dict."""

    def __init__(self, spaces: Dict[str, Any], seed: int = 0):
        self._spaces = spaces
        self._rng = np.random.default_rng(seed)

    def sample(self) -> Dict[str, Any]:
        return {k: s.sample(self._rng) for k, s in self._spaces.items()}


class GridSpace:
    """Exhaustive cartesian grid over a space dict."""

    def __init__(self, spaces: Dict[str, Any]):
        import itertools
        names = list(spaces)
        grids = [spaces[n].grid() if hasattr(spaces[n], "grid")
                 else list(spaces[n]) for n in names]
        self._points = [dict(zip(names, combo))
                        for combo in itertools.product(*grids)]

    def __len__(self) -> int:
        return len(self._points)

    def __iter__(self):
        return iter(self._points)


class TuneHyperparameters(_EvalParams, HasSeed, Estimator):
    """Random/grid search with parallel fits
    (automl/TuneHyperparameters.scala)."""

    numRuns = Param("numRuns", "Number of random candidates", default=10,
                    typeConverter=TypeConverters.toInt)
    parallelism = Param("parallelism", "Concurrent fits", default=4,
                        typeConverter=TypeConverters.toInt)
    numFolds = Param("numFolds", "Cross-validation folds (1 = holdout)",
                     default=3, typeConverter=TypeConverters.toInt)
    searchMode = Param("searchMode", "random or grid", default="random",
                       typeConverter=TypeConverters.toString,
                       validator=lambda v: v in ("random", "grid"))

    def __init__(self, models: Optional[Sequence[Estimator]] = None,
                 hyperParams: Optional[Dict[str, Any]] = None, **kwargs):
        super().__init__(**kwargs)
        self._models = list(models or [])
        self._hyper = dict(hyperParams or {})

    def setModels(self, models) -> "TuneHyperparameters":
        self._models = list(models)
        return self

    def setHyperParams(self, spaces: Dict[str, Any]) -> "TuneHyperparameters":
        self._hyper = dict(spaces)
        return self

    def _save_extra(self, path: str) -> None:
        serialize.save_stage_list(self._models, os.path.join(path, "models"))
        serialize.save_json(path, "spaces",
                            {k: _space_to_json(s)
                             for k, s in self._hyper.items()})

    def _load_extra(self, path: str) -> None:
        p = os.path.join(path, "models")
        self._models = serialize.load_stage_list(p) if os.path.exists(p) \
            else []
        try:
            spaces = serialize.load_json(path, "spaces")
        except FileNotFoundError:
            spaces = {}
        self._hyper = {k: _space_from_json(v) for k, v in spaces.items()}

    def _candidates(self) -> List[Dict[str, Any]]:
        if self.getSearchMode() == "grid":
            return list(GridSpace(self._hyper))
        space = RandomSpace(self._hyper, seed=self.getSeed())
        return [space.sample() for _ in range(self.getNumRuns())]

    def _fit(self, table: DataTable) -> "TuneHyperparametersModel":
        if not self._models:
            raise ValueError("TuneHyperparameters needs base models")
        metric = self.getEvaluationMetric()
        maximize = _METRIC_COL[metric] in _MAXIMIZE
        folds = max(1, self.getNumFolds())
        n = len(table)
        rng = np.random.default_rng(self.getSeed())
        perm = rng.permutation(n)
        fold_of = np.arange(n) % folds

        def eval_candidate(args):
            est, params = args
            cand = est.copy({k: v for k, v in params.items()
                             if est.hasParam(k)})
            vals = []
            for f in range(folds):
                if folds == 1:
                    cut = max(1, int(0.8 * n))
                    train_idx, val_idx = perm[:cut], perm[cut:]
                else:
                    train_idx = perm[fold_of != f]
                    val_idx = perm[fold_of == f]
                fitted = cand._fit(table.take(train_idx))
                vals.append(_evaluate(fitted, table.take(val_idx), metric,
                                      self.getLabelCol()))
            return float(np.mean(vals)), cand

        jobs = [(est, params) for est in self._models
                for params in self._candidates()]
        with ThreadPoolExecutor(max_workers=self.getParallelism()) as pool:
            results = list(pool.map(eval_candidate, jobs))

        scores = np.asarray([v for v, _ in results])
        # NaN folds (e.g. single-class validation split) must never win
        scores = np.where(np.isnan(scores),
                          -np.inf if maximize else np.inf, scores)
        if not np.isfinite(scores).any():
            raise ValueError(
                "Every candidate produced a NaN metric; check that "
                "validation folds contain both classes")
        best_i = int(np.argmax(scores) if maximize else np.argmin(scores))
        best_val, best_est = results[best_i]
        fitted = best_est._fit(table)  # refit on all rows
        model = TuneHyperparametersModel(
            fitted=fitted, metric_value=best_val,
            best_params={k: v for k, v in jobs[best_i][1].items()})
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class TuneHyperparametersModel(_EvalParams, Model):
    def __init__(self, fitted: Optional[Transformer] = None,
                 metric_value: Optional[float] = None,
                 best_params: Optional[Dict[str, Any]] = None, **kwargs):
        super().__init__(**kwargs)
        self._fitted = fitted
        self._metric_value = metric_value
        self._best_params = dict(best_params or {})

    def getBestModel(self) -> Transformer:
        return self._fitted

    def getBestModelMetrics(self) -> Optional[float]:
        return self._metric_value

    def getBestModelInfo(self) -> Dict[str, Any]:
        return dict(self._best_params)

    def _transform(self, table: DataTable) -> DataTable:
        return self._fitted._transform(table)

    def _save_extra(self, path: str) -> None:
        serialize.save_stage(self._fitted, os.path.join(path, "best"),
                             overwrite=True)
        serialize.save_json(path, "results", {
            "metric_value": self._metric_value,
            "best_params": self._best_params})

    def _load_extra(self, path: str) -> None:
        self._fitted = serialize.load_stage(os.path.join(path, "best"))
        info = serialize.load_json(path, "results")
        self._metric_value = info["metric_value"]
        self._best_params = info["best_params"]
