"""AutoML (reference ``automl/`` package).

Reference: automl/FindBestModel.scala, automl/TuneHyperparameters.scala,
automl/HyperparamBuilder.scala (expected paths, UNVERIFIED — SURVEY.md
§2.1).
"""

from .automl import (
    BestModel,
    DiscreteHyperParam,
    FindBestModel,
    GridSpace,
    HyperparamBuilder,
    RandomSpace,
    RangeHyperParam,
    TuneHyperparameters,
    TuneHyperparametersModel,
)

__all__ = [
    "BestModel", "DiscreteHyperParam", "FindBestModel", "GridSpace",
    "HyperparamBuilder", "RandomSpace", "RangeHyperParam",
    "TuneHyperparameters", "TuneHyperparametersModel",
]
