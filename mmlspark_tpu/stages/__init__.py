"""Utility pipeline stages (reference ``stages/`` package).

Reference: src/main/scala/com/microsoft/ml/spark/stages/*.scala (expected
paths, UNVERIFIED — SURVEY.md §2.1): ~20 small transformers for column
manipulation, batching, partitioning, timing, and text cleanup.
"""

from .stages import (
    Cacher,
    DropColumns,
    EnsembleByKey,
    Explode,
    FixedMiniBatchTransformer,
    MiniBatchTransformer,
    FlattenBatch,
    Lambda,
    MultiColumnAdapter,
    MultiColumnAdapterModel,
    RenameColumn,
    Repartition,
    SelectColumns,
    StratifiedRepartition,
    SummarizeData,
    TextPreprocessor,
    Timer,
    UDFTransformer,
    UnicodeNormalize,
)

__all__ = [
    "Cacher", "DropColumns", "EnsembleByKey", "Explode",
    "FixedMiniBatchTransformer", "MiniBatchTransformer",
    "FlattenBatch", "Lambda",
    "MultiColumnAdapter", "MultiColumnAdapterModel", "RenameColumn",
    "Repartition", "SelectColumns",
    "StratifiedRepartition", "SummarizeData", "TextPreprocessor", "Timer",
    "UDFTransformer", "UnicodeNormalize",
]
