"""Utility transformer implementations.

Reference: stages/*.scala (expected paths, UNVERIFIED — SURVEY.md §2.1).
Columnar analogs of the reference's DataFrame helpers.  Spark-specific
notions map as follows: a "partition" here is a contiguous row block (rows
are host numpy; device sharding happens inside learners), "caching" is
materialization (numpy is already materialized, so Cacher is a checkpoint
of the current table).
"""

from __future__ import annotations

import time
import unicodedata
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.params import (HasInputCol, HasInputCols, HasOutputCol, Param,
                           TypeConverters)
from ..core.pipeline import (Estimator, Model, PipelineStage, Transformer)
from ..core.schema import DataTable
from ..core import serialize


# -- column selection ---------------------------------------------------------

class DropColumns(Transformer):
    """Drops columns (stages/DropColumns.scala)."""
    cols = Param("cols", "Columns to drop",
                 typeConverter=TypeConverters.toListString)

    def _transform(self, table: DataTable) -> DataTable:
        return table.drop(*self.getCols())


class SelectColumns(Transformer):
    """Keeps only the listed columns (stages/SelectColumns.scala)."""
    cols = Param("cols", "Columns to keep",
                 typeConverter=TypeConverters.toListString)

    def _transform(self, table: DataTable) -> DataTable:
        return table.select(*self.getCols())


class RenameColumn(HasInputCol, HasOutputCol, Transformer):
    """Renames a column (stages/RenameColumn.scala)."""

    def _transform(self, table: DataTable) -> DataTable:
        return table.rename({self.getInputCol(): self.getOutputCol()})


# -- row manipulation ---------------------------------------------------------

class Repartition(Transformer):
    """Round-robin reorder of rows into ``n`` contiguous blocks — the
    columnar analog of Spark's shuffle repartition (stages/Repartition.scala).
    Block boundaries are what downstream device sharding consumes."""

    n = Param("n", "Number of partitions", typeConverter=TypeConverters.toInt,
              validator=lambda v: v > 0)

    def _transform(self, table: DataTable) -> DataTable:
        n = self.getN()
        rows = len(table)
        # round-robin: row i goes to block i % n; stable within a block
        order = np.argsort(np.arange(rows) % n, kind="stable")
        return table.take(order)


class StratifiedRepartition(Transformer):
    """Reorders rows so every contiguous block sees the full label mix
    (stages/StratifiedRepartition.scala — used to guarantee each LightGBM
    worker sees every class)."""

    labelCol = Param("labelCol", "Label column", default="label",
                     typeConverter=TypeConverters.toString)
    mode = Param("mode", "Equal, original or mixed ratios", default="mixed",
                 typeConverter=TypeConverters.toString)

    def _transform(self, table: DataTable) -> DataTable:
        y = table[self.getLabelCol()]
        # interleave classes: stable sort by within-class sequence number
        _, inverse = np.unique(y, return_inverse=True)
        seq = np.zeros(len(y), dtype=np.int64)
        counters: Dict[int, int] = {}
        for i, c in enumerate(inverse):
            counters[c] = counters.get(c, 0) + 1
            seq[i] = counters[c]
        order = np.lexsort((inverse, seq))
        return table.take(order)


class Explode(HasInputCol, HasOutputCol, Transformer):
    """Replicates each row once per element of a list column
    (stages/Explode.scala)."""

    def _transform(self, table: DataTable) -> DataTable:
        col = table[self.getInputCol()]
        out_col = self._peek("outputCol") or self.getInputCol()
        lengths = np.asarray([len(v) for v in col], dtype=np.int64)
        idx = np.repeat(np.arange(len(table)), lengths)
        exploded = np.empty(int(lengths.sum()), dtype=object)
        k = 0
        for v in col:
            for item in v:
                exploded[k] = item
                k += 1
        out = table.take(idx)
        return out.withColumn(out_col, exploded)


class Cacher(Transformer):
    """Materialization checkpoint (stages/Cacher.scala).  numpy tables are
    eager already; this snapshots columns so later in-place mutation by
    foreign code cannot leak backwards."""

    disable = Param("disable", "Pass through without caching", default=False,
                    typeConverter=TypeConverters.toBool)

    def _transform(self, table: DataTable) -> DataTable:
        if self.getDisable():
            return table
        return DataTable({k: np.copy(table[k]) for k in table.columns})


# -- functional stages --------------------------------------------------------

class UDFTransformer(HasInputCol, HasInputCols, HasOutputCol, Transformer):
    """Applies a python function to one column (rowwise) or several columns
    (rowwise over tuples) — stages/UDFTransformer.scala.  The function
    persists via cloudpickle (Spark's pickled-Python-UDF contract: load in
    an environment providing the modules it closes over)."""

    _udf: Optional[Callable] = None  # survives load_stage's __new__ path

    def __init__(self, udf: Optional[Callable] = None, **kwargs):
        super().__init__(**kwargs)
        self._udf = udf

    def _save_extra(self, path: str) -> None:
        if self._udf is not None:
            serialize.save_callable(path, "udf", self._udf)

    def _load_extra(self, path: str) -> None:
        self._udf = serialize.load_callable(path, "udf")

    def setUDF(self, udf: Callable) -> "UDFTransformer":
        self._udf = udf
        return self

    def getUDF(self) -> Optional[Callable]:
        return self._udf

    def _transform(self, table: DataTable) -> DataTable:
        if self._udf is None:
            raise ValueError("UDFTransformer has no UDF; call setUDF(fn)")
        if self.isSet("inputCols"):
            cols = [table[c] for c in self.getInputCols()]
            out = np.asarray([self._udf(*vals) for vals in zip(*cols)])
        else:
            col = table[self.getInputCol()]
            out = np.asarray([self._udf(v) for v in col])
        return table.withColumn(self.getOutputCol(), out)


class Lambda(Transformer):
    """Arbitrary table→table function (stages/Lambda.scala).  The function
    persists via cloudpickle, same contract as UDFTransformer."""

    _fn: Optional[Callable] = None  # survives load_stage's __new__ path

    def __init__(self, transformFunc: Optional[Callable] = None, **kwargs):
        super().__init__(**kwargs)
        self._fn = transformFunc

    def _save_extra(self, path: str) -> None:
        if self._fn is not None:
            serialize.save_callable(path, "fn", self._fn)

    def _load_extra(self, path: str) -> None:
        self._fn = serialize.load_callable(path, "fn")

    def setTransform(self, fn: Callable) -> "Lambda":
        self._fn = fn
        return self

    def _transform(self, table: DataTable) -> DataTable:
        if self._fn is None:
            raise ValueError("Lambda has no function; call setTransform(fn)")
        out = self._fn(table)
        if not isinstance(out, DataTable):
            out = DataTable(out)
        return out


class MultiColumnAdapter(Estimator):
    """Applies a single-column base stage to many columns
    (stages/MultiColumnAdapter.scala).  Like the reference this is an
    Estimator: an Estimator base stage is fit ONCE per column at fit time,
    and the fitted per-column models are frozen in the returned
    :class:`MultiColumnAdapterModel` — scoring data never refits."""

    inputCols = Param("inputCols", "Input columns",
                      typeConverter=TypeConverters.toListString)
    outputCols = Param("outputCols", "Output columns",
                       typeConverter=TypeConverters.toListString)

    def __init__(self, baseStage: Optional[PipelineStage] = None, **kwargs):
        super().__init__(**kwargs)
        self._base = baseStage

    def getBaseStage(self) -> Optional[PipelineStage]:
        return self._base

    # convenience: transformer-only base stages can skip the explicit fit
    def transform(self, dataset) -> DataTable:
        if isinstance(self._base, Estimator):
            raise TypeError(
                "baseStage is an Estimator; call fit(...) first so the "
                "per-column models are frozen before scoring")
        return self.fit(dataset).transform(dataset)

    def _fit(self, table: DataTable) -> "MultiColumnAdapterModel":
        ins, outs = self.getInputCols(), self.getOutputCols()
        if len(ins) != len(outs):
            raise ValueError("inputCols and outputCols must align")
        fitted: List[Transformer] = []
        current = table
        for i, o in zip(ins, outs):
            stage = self._base.copy()
            stage.set("inputCol", i)
            stage.set("outputCol", o)
            if isinstance(stage, Estimator):
                stage = stage._fit(current)
            fitted.append(stage)
            current = stage._transform(current)
        model = MultiColumnAdapterModel(stages=fitted)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model

    def _save_extra(self, path: str) -> None:
        import os
        if self._base is not None:
            serialize.save_stage(self._base, os.path.join(path, "base"),
                                 overwrite=True)

    def _load_extra(self, path: str) -> None:
        import os
        base = os.path.join(path, "base")
        self._base = serialize.load_stage(base) if os.path.exists(base) \
            else None


class MultiColumnAdapterModel(Model):
    """Frozen per-column stages produced by :class:`MultiColumnAdapter`."""

    def __init__(self, stages: Optional[List[Transformer]] = None, **kwargs):
        super().__init__(**kwargs)
        self._stages = list(stages or [])

    @property
    def stages(self) -> List[Transformer]:
        return list(self._stages)

    def _transform(self, table: DataTable) -> DataTable:
        for stage in self._stages:
            table = stage._transform(table)
        return table

    def _save_extra(self, path: str) -> None:
        import os
        serialize.save_stage_list(self._stages, os.path.join(path, "stages"))

    def _load_extra(self, path: str) -> None:
        import os
        self._stages = serialize.load_stage_list(os.path.join(path, "stages"))


class Timer(Transformer):
    """Wraps a stage and records its wall time (stages/Timer.scala).
    Timings accumulate in ``.timings`` and log to stdout when logToScala
    (kept name for parity) is set."""

    logToScala = Param("logToScala", "Print timing lines", default=True,
                       typeConverter=TypeConverters.toBool)

    def __init__(self, stage: Optional[Transformer] = None, **kwargs):
        super().__init__(**kwargs)
        self._stage = stage
        self.timings: List[float] = []

    def getStage(self) -> Optional[Transformer]:
        return self._stage

    def _transform(self, table: DataTable) -> DataTable:
        if self._stage is None:
            raise ValueError("Timer wraps no stage")
        t0 = time.perf_counter()
        out = self._stage._transform(table)
        dt = time.perf_counter() - t0
        self.timings.append(dt)
        if self.getLogToScala():
            print(f"[Timer] {type(self._stage).__name__}.transform: {dt:.4f}s")
        return out

    def _save_extra(self, path: str) -> None:
        import os
        if self._stage is not None:
            serialize.save_stage(self._stage, os.path.join(path, "stage"),
                                 overwrite=True)

    def _load_extra(self, path: str) -> None:
        import os
        p = os.path.join(path, "stage")
        self._stage = serialize.load_stage(p) if os.path.exists(p) else None
        self.timings = []


# -- aggregation --------------------------------------------------------------

class EnsembleByKey(Transformer):
    """Groups rows by key columns and aggregates value columns
    (stages/EnsembleByKey.scala — used to merge per-model scores)."""

    keys = Param("keys", "Key columns",
                 typeConverter=TypeConverters.toListString)
    cols = Param("cols", "Value columns to aggregate",
                 typeConverter=TypeConverters.toListString)
    strategy = Param("strategy", "Aggregation strategy", default="mean",
                     typeConverter=TypeConverters.toString,
                     validator=lambda v: v in ("mean", "sum", "max", "min"))
    collapseGroup = Param("collapseGroup",
                          "Return one row per group (else broadcast back)",
                          default=True, typeConverter=TypeConverters.toBool)

    def _transform(self, table: DataTable) -> DataTable:
        keys, cols = self.getKeys(), self.getCols()
        key_arrays = [table[k] for k in keys]
        key_tuples = list(zip(*[a.tolist() for a in key_arrays]))
        uniq: Dict[Any, int] = {}
        group_of = np.empty(len(table), dtype=np.int64)
        for i, kt in enumerate(key_tuples):
            group_of[i] = uniq.setdefault(kt, len(uniq))
        n_groups = len(uniq)
        fn = {"mean": np.mean, "sum": np.sum, "max": np.max,
              "min": np.min}[self.getStrategy()]
        agg: Dict[str, np.ndarray] = {}
        for c in cols:
            col = np.asarray(table[c], dtype=np.float64)
            rows = [fn(col[group_of == g], axis=0) for g in range(n_groups)]
            agg[f"{self.getStrategy()}({c})"] = np.asarray(rows)
        if self.getCollapseGroup():
            out_cols: Dict[str, Any] = {}
            first_idx = np.asarray(
                [np.flatnonzero(group_of == g)[0] for g in range(n_groups)])
            for k in keys:
                out_cols[k] = table[k][first_idx]
            out_cols.update(agg)
            return DataTable(out_cols)
        new = {name: vals[group_of] for name, vals in agg.items()}
        return table.withColumns(new)


class SummarizeData(Transformer):
    """Dataset statistics as a table (stages/SummarizeData.scala): one row
    per column with counts/missing/basic stats/percentiles."""

    basic = Param("basic", "Include basic stats", default=True,
                  typeConverter=TypeConverters.toBool)
    counts = Param("counts", "Include counts", default=True,
                   typeConverter=TypeConverters.toBool)
    percentiles = Param("percentiles", "Include percentiles", default=True,
                        typeConverter=TypeConverters.toBool)
    errorThreshold = Param("errorThreshold",
                           "Percentile accuracy (parity param; exact here)",
                           default=0.0, typeConverter=TypeConverters.toFloat)

    def _transform(self, table: DataTable) -> DataTable:
        names, stats = [], {k: [] for k in (
            "count", "unique_value_count", "missing_value_count", "mean",
            "stddev", "min", "max", "p25", "median", "p75")}
        for name in table.columns:
            col = table[name]
            if col.ndim != 1:
                continue
            names.append(name)
            numeric = col.dtype.kind in "fiub"
            colf = col.astype(np.float64) if numeric else None
            missing = int(np.isnan(colf).sum()) if numeric \
                else sum(v is None for v in col)
            stats["count"].append(len(col))
            stats["unique_value_count"].append(
                len(np.unique(col[~np.isnan(colf)])) if numeric
                else len(set(col) - {None}))
            stats["missing_value_count"].append(missing)
            for key, fn in (("mean", np.nanmean), ("stddev", np.nanstd),
                            ("min", np.nanmin), ("max", np.nanmax)):
                stats[key].append(float(fn(colf)) if numeric else np.nan)
            for key, q in (("p25", 25), ("median", 50), ("p75", 75)):
                stats[key].append(
                    float(np.nanpercentile(colf, q)) if numeric else np.nan)
        out: Dict[str, Any] = {"column": np.asarray(names, dtype=object)}
        if self.getCounts():
            for k in ("count", "unique_value_count", "missing_value_count"):
                out[k] = np.asarray(stats[k], dtype=np.float64)
        if self.getBasic():
            for k in ("mean", "stddev", "min", "max"):
                out[k] = np.asarray(stats[k], dtype=np.float64)
        if self.getPercentiles():
            for k in ("p25", "median", "p75"):
                out[k] = np.asarray(stats[k], dtype=np.float64)
        return DataTable(out)


# -- text cleanup -------------------------------------------------------------

class TextPreprocessor(HasInputCol, HasOutputCol, Transformer):
    """Longest-match string replacement via a trie
    (stages/TextPreprocessor.scala)."""

    map = Param("map", "Replacement mapping {pattern: replacement}",
                default=None)
    normFunc = Param("normFunc", "Normalization: identity|lowerCase|trim",
                     default="identity", typeConverter=TypeConverters.toString,
                     validator=lambda v: v in ("identity", "lowerCase", "trim"))

    def _apply_norm(self, s: str) -> str:
        fn = self.getNormFunc()
        if fn == "lowerCase":
            return s.lower()
        if fn == "trim":
            return s.strip()
        return s

    def _replace(self, s: str, mapping: Dict[str, str]) -> str:
        if not mapping:
            return s
        # longest-match-first scan (trie semantics without the trie)
        keys = sorted(mapping, key=len, reverse=True)
        out, i = [], 0
        while i < len(s):
            for k in keys:
                if s.startswith(k, i):
                    out.append(mapping[k])
                    i += len(k)
                    break
            else:
                out.append(s[i])
                i += 1
        return "".join(out)

    def _transform(self, table: DataTable) -> DataTable:
        mapping = self.getMap() or {}
        col = table[self.getInputCol()]
        out = np.asarray(
            [self._replace(self._apply_norm(str(v)), mapping) for v in col],
            dtype=object)
        return table.withColumn(self.getOutputCol(), out)


class UnicodeNormalize(HasInputCol, HasOutputCol, Transformer):
    """Unicode normalization (stages/UnicodeNormalize.scala)."""

    form = Param("form", "Normalization form: NFC|NFD|NFKC|NFKD",
                 default="NFKD", typeConverter=TypeConverters.toString,
                 validator=lambda v: v in ("NFC", "NFD", "NFKC", "NFKD"))
    lower = Param("lower", "Lowercase the result", default=True,
                  typeConverter=TypeConverters.toBool)

    def _transform(self, table: DataTable) -> DataTable:
        col = table[self.getInputCol()]
        form = self.getForm()
        out = []
        for v in col:
            s = unicodedata.normalize(form, str(v))
            out.append(s.lower() if self.getLower() else s)
        return table.withColumn(self.getOutputCol(),
                                np.asarray(out, dtype=object))


# -- batching -----------------------------------------------------------------

class FixedMiniBatchTransformer(Transformer):
    """Packs rows into fixed-size batches: every column becomes an object
    column of per-batch arrays (stages/MiniBatchTransformer.scala).  The
    device-friendly shape for JNI/HTTP-style stages in the reference; here
    it feeds jit'd models fixed-size chunks (static shapes → one XLA
    compile)."""

    batchSize = Param("batchSize", "Rows per batch",
                      typeConverter=TypeConverters.toInt,
                      validator=lambda v: v > 0)

    def _transform(self, table: DataTable) -> DataTable:
        bs = self.getBatchSize()
        n = len(table)
        n_batches = (n + bs - 1) // bs
        cols: Dict[str, Any] = {}
        for name in table.columns:
            col = table[name]
            batched = np.empty(n_batches, dtype=object)
            for b in range(n_batches):
                batched[b] = col[b * bs:(b + 1) * bs]
            cols[name] = batched
        return DataTable(cols)


class MiniBatchTransformer(FixedMiniBatchTransformer):
    """Reference-name alias: stages/MiniBatchTransformer.scala's default
    batcher is the fixed-size one."""


class FlattenBatch(Transformer):
    """Inverse of the mini-batchers (stages/FlattenBatch.scala)."""

    def _transform(self, table: DataTable) -> DataTable:
        cols: Dict[str, Any] = {}
        for name in table.columns:
            parts = [np.asarray(p) for p in table[name]]
            cols[name] = np.concatenate(parts, axis=0) if parts \
                else np.empty(0)
        return DataTable(cols)
