"""Nearest neighbors (reference ``nn/`` package).

Reference: nn/BallTree.scala, nn/KNN.scala, nn/ConditionalKNN.scala
(expected paths, UNVERIFIED — SURVEY.md §2.1).
"""

from .knn import KNN, KNNModel, ConditionalKNN, ConditionalKNNModel
from .balltree import BallTree

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel",
           "BallTree"]
