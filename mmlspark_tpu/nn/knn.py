"""KNN / ConditionalKNN — brute-force matmul distances + top_k on device.

Reference: nn/KNN.scala, nn/ConditionalKNN.scala (expected paths,
UNVERIFIED — SURVEY.md §2.1).  The reference broadcasts a BallTree and
queries per row on each executor; the TPU-native design computes
``‖q−x‖² = ‖q‖² − 2 q·xᵀ + ‖x‖²`` — one (Q × F)·(F × N) MXU matmul per
query batch — and takes ``lax.top_k``.  Exact, batched, and faster than
tree traversal for the dimensionalities the reference targets (feature
vectors from DNN featurization).

ConditionalKNN restricts matches to rows whose label is in each query's
allowed set, implemented as an additive mask before top_k.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import (HasFeaturesCol, HasLabelCol, HasOutputCol, Param,
                           TypeConverters)
from ..core.pipeline import Estimator, Model
from ..core.schema import DataTable, features_matrix
from ..core import serialize


@partial(jax.jit, static_argnames=("k",))
def _knn(Q, X, k: int):
    """(Q, F), (N, F) → (dists², idx) of k nearest per query row."""
    d2 = (jnp.sum(Q * Q, axis=1, keepdims=True)
          - 2.0 * Q @ X.T
          + jnp.sum(X * X, axis=1)[None, :])
    neg, idx = jax.lax.top_k(-d2, k)
    return -neg, idx


@partial(jax.jit, static_argnames=("k",))
def _conditional_knn(Q, X, mask, k: int):
    """mask: (Q, N) bool — True where candidate row is allowed."""
    d2 = (jnp.sum(Q * Q, axis=1, keepdims=True)
          - 2.0 * Q @ X.T
          + jnp.sum(X * X, axis=1)[None, :])
    d2 = jnp.where(mask, d2, jnp.inf)
    neg, idx = jax.lax.top_k(-d2, k)
    return -neg, idx


class _KNNParams(HasFeaturesCol, HasOutputCol):
    valuesCol = Param("valuesCol",
                      "Column whose values are returned for matches",
                      default=None, typeConverter=TypeConverters.toString)
    outputCol = Param("outputCol", "Output column of matches",
                      default="matches", typeConverter=TypeConverters.toString)
    k = Param("k", "Number of matches", default=5,
              typeConverter=TypeConverters.toInt)
    leafSize = Param("leafSize",
                     "BallTree leaf size (parity param; the device path is "
                     "brute-force exact)", default=50,
                     typeConverter=TypeConverters.toInt)


class KNN(_KNNParams, Estimator):
    """Exact k-nearest-neighbors (nn/KNN.scala)."""

    def _fit(self, table: DataTable) -> "KNNModel":
        X = np.asarray(features_matrix(table, self.getFeaturesCol()),
                       dtype=np.float32)
        values_col = self.getValuesCol()
        values = (np.asarray(table[values_col]) if values_col else None)
        model = KNNModel(points=X, values=values)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class KNNModel(_KNNParams, Model):
    def __init__(self, points: Optional[np.ndarray] = None,
                 values: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self._X = points
        self._values = values

    def _transform(self, table: DataTable) -> DataTable:
        Q = np.asarray(features_matrix(table, self.getFeaturesCol()),
                       dtype=np.float32)
        k = min(self.getK(), len(self._X))
        d2, idx = _knn(jnp.asarray(Q), jnp.asarray(self._X), k)
        d = np.sqrt(np.maximum(np.asarray(d2), 0.0))
        idx = np.asarray(idx, dtype=np.int64)
        out = {self.getOutputCol(): idx, "distances": d.astype(np.float64)}
        if self._values is not None:
            vals = np.empty(len(Q), dtype=object)
            for r in range(len(Q)):
                vals[r] = [self._values[i] for i in idx[r]]
            out["values"] = vals
        return table.withColumns(out)

    def _save_extra(self, path: str) -> None:
        arrays = {"points": self._X}
        if self._values is not None and self._values.dtype != object:
            arrays["values"] = self._values
        serialize.save_arrays(path, **arrays)
        if self._values is not None and self._values.dtype == object:
            # JSON keeps the value types (ints stay ints, lists stay lists);
            # a non-JSON-serializable payload raises instead of corrupting
            serialize.save_json(path, "values_obj", list(self._values))

    def _load_extra(self, path: str) -> None:
        import os
        arrays = serialize.load_arrays(path)
        self._X = arrays["points"]
        self._values = arrays.get("values")
        obj_path = os.path.join(path, "values_obj.json")
        if self._values is None and os.path.exists(obj_path):
            loaded = serialize.load_json(path, "values_obj")
            self._values = np.empty(len(loaded), dtype=object)
            self._values[:] = loaded


class ConditionalKNN(_KNNParams, HasLabelCol, Estimator):
    """KNN where matches must carry a label from the query's allowed set
    (nn/ConditionalKNN.scala)."""

    conditionerCol = Param("conditionerCol",
                           "Query column of allowed label sets",
                           default="conditioner",
                           typeConverter=TypeConverters.toString)

    def _fit(self, table: DataTable) -> "ConditionalKNNModel":
        X = np.asarray(features_matrix(table, self.getFeaturesCol()),
                       dtype=np.float32)
        labels = np.asarray(table[self.getLabelCol()])
        values_col = self.getValuesCol()
        values = (np.asarray(table[values_col]) if values_col else None)
        model = ConditionalKNNModel(points=X, labels=labels, values=values)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class ConditionalKNNModel(_KNNParams, HasLabelCol, Model):
    conditionerCol = ConditionalKNN.conditionerCol

    def __init__(self, points: Optional[np.ndarray] = None,
                 labels: Optional[np.ndarray] = None,
                 values: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self._X = points
        self._labels = labels
        self._values = values

    def _transform(self, table: DataTable) -> DataTable:
        Q = np.asarray(features_matrix(table, self.getFeaturesCol()),
                       dtype=np.float32)
        cond = table[self.getConditionerCol()]
        k = min(self.getK(), len(self._X))
        # (Q, N) allowed mask on host (labels are arbitrary objects)
        mask = np.zeros((len(Q), len(self._X)), dtype=bool)
        for r, allowed in enumerate(cond):
            allowed_set = set(np.asarray(allowed).tolist()
                              if isinstance(allowed, (list, tuple, np.ndarray))
                              else [allowed])
            mask[r] = np.isin(self._labels, list(allowed_set))
        d2, idx = _conditional_knn(jnp.asarray(Q), jnp.asarray(self._X),
                                   jnp.asarray(mask), k)
        d2 = np.asarray(d2)
        idx = np.asarray(idx, dtype=np.int64)
        valid = np.isfinite(d2)
        d = np.sqrt(np.maximum(d2, 0.0))
        matches = np.empty(len(Q), dtype=object)
        dists = np.empty(len(Q), dtype=object)
        labels_out = np.empty(len(Q), dtype=object)
        for r in range(len(Q)):
            keep = valid[r]
            matches[r] = idx[r][keep].tolist()
            dists[r] = d[r][keep].tolist()
            labels_out[r] = [self._labels[i] for i in idx[r][keep]]
        out = {self.getOutputCol(): matches, "distances": dists,
               "labels": labels_out}
        if self._values is not None:
            vals = np.empty(len(Q), dtype=object)
            for r in range(len(Q)):
                vals[r] = [self._values[i] for i in matches[r]]
            out["values"] = vals
        return table.withColumns(out)

    def _save_extra(self, path: str) -> None:
        serialize.save_arrays(path, points=self._X)
        serialize.save_json(path, "labels",
                            np.asarray(self._labels).tolist())
        if self._values is not None:
            serialize.save_json(path, "values", list(self._values))

    def _load_extra(self, path: str) -> None:
        import os
        self._X = serialize.load_arrays(path)["points"]
        self._labels = np.asarray(serialize.load_json(path, "labels"))
        self._values = None
        if os.path.exists(os.path.join(path, "values.json")):
            loaded = serialize.load_json(path, "values")
            self._values = np.empty(len(loaded), dtype=object)
            self._values[:] = loaded
