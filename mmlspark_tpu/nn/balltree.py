"""BallTree — host-side exact NN structure for API parity.

Reference: nn/BallTree.scala (expected path, UNVERIFIED — SURVEY.md §2.1).
The reference broadcasts a serialized BallTree to executors and queries it
per row on the JVM.  On TPU the *fast* path is the brute-force matmul in
:mod:`mmlspark_tpu.nn.knn` (distance = one MXU matmul + top_k, batched over
queries); this class exists for users of the reference's BallTree API and
for host-side queries on datasets too small to ship to the device.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("center", "radius", "idx", "left", "right")

    def __init__(self, center, radius, idx, left=None, right=None):
        self.center = center
        self.radius = radius
        self.idx = idx          # leaf: indices array; internal: None
        self.left = left
        self.right = right


class BallTree:
    """Exact k-NN ball tree over a point matrix (euclidean)."""

    def __init__(self, points: np.ndarray, leaf_size: int = 50):
        self._pts = np.asarray(points, dtype=np.float64)
        self._leaf_size = int(leaf_size)
        self._root = self._build(np.arange(len(self._pts)))

    def _build(self, idx: np.ndarray) -> _Node:
        pts = self._pts[idx]
        center = pts.mean(axis=0)
        radius = float(np.sqrt(((pts - center) ** 2).sum(axis=1).max())) \
            if len(pts) else 0.0
        if len(idx) <= self._leaf_size:
            return _Node(center, radius, idx)
        # split on the direction of max spread
        spread = pts.max(axis=0) - pts.min(axis=0)
        dim = int(np.argmax(spread))
        order = np.argsort(pts[:, dim], kind="stable")
        half = len(idx) // 2
        left = self._build(idx[order[:half]])
        right = self._build(idx[order[half:]])
        return _Node(center, radius, None, left, right)

    def query(self, q: np.ndarray, k: int = 1
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (distances, indices) of the k nearest points to q."""
        q = np.asarray(q, dtype=np.float64)
        best: List[Tuple[float, int]] = []   # max-heap by -dist (small list)

        def visit(node: _Node):
            d_center = float(np.sqrt(((q - node.center) ** 2).sum()))
            if len(best) == k and d_center - node.radius > best[-1][0]:
                return  # prune: ball cannot contain anything closer
            if node.idx is not None:
                d = np.sqrt(((self._pts[node.idx] - q) ** 2).sum(axis=1))
                for dist, i in zip(d, node.idx):
                    if len(best) < k:
                        best.append((float(dist), int(i)))
                        best.sort()
                    elif dist < best[-1][0]:
                        best[-1] = (float(dist), int(i))
                        best.sort()
                return
            # nearer child first
            d_l = ((q - node.left.center) ** 2).sum()
            d_r = ((q - node.right.center) ** 2).sum()
            first, second = ((node.left, node.right) if d_l <= d_r
                             else (node.right, node.left))
            visit(first)
            visit(second)

        visit(self._root)
        dists = np.asarray([d for d, _ in best])
        idxs = np.asarray([i for _, i in best], dtype=np.int64)
        return dists, idxs
