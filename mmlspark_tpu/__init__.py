"""mmlspark_tpu — a TPU-native framework with the capabilities of mmlspark.

A from-scratch re-design of the reference (dbanda/mmlspark, a Spark/JVM
library bridging native C++ ML engines over SWIG/JNI) for TPU hardware:
the histogram GBDT engine is built directly in JAX/XLA/Pallas, distributed
training uses compiler-scheduled ICI/DCN collectives over a
``jax.sharding.Mesh`` instead of LightGBM's raw TCP socket allreduce, and
DNN inference transformers run via ``jax.jit``.  The user-facing API mirrors
mmlspark's stage names and params so existing pipelines port directly.

See SURVEY.md at the repo root for the reference layer map this build tracks.
"""

__version__ = "0.5.0"

from . import core
from .core import (DataTable, Pipeline, PipelineModel, Estimator, Transformer,
                   Model)

__all__ = ["core", "DataTable", "Pipeline", "PipelineModel", "Estimator",
           "Transformer", "Model", "__version__"]
