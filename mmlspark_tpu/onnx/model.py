"""ONNXModel: score ONNX graphs with jax/XLA on TPU.

TPU-native replacement for the reference's onnxruntime-JNI transformer
(onnx/ONNXModel.scala, expected path, UNVERIFIED; SURVEY.md §2.1): the graph
is parsed (mmlspark_tpu/onnx/proto.py), converted node-by-node to jax ops,
and the whole forward is one jitted XLA program — operator fusion comes from
the compiler rather than onnxruntime's executor.  Supports the core
CNN/MLP operator set (Conv, Gemm/MatMul, BatchNorm, pooling, activations,
elementwise, Reshape/Flatten/Concat/Transpose, Softmax, LRN, Dropout-as-
identity) plus the tensor-manipulation tier (Gather/GatherElements, Shape, Slice,
Split, the full Reduce* family, Arg*, TopK, CumSum, OneHot, Where,
comparisons/logicals, Expand, Tile, ConstantOfShape, Range, Pad,
LayerNormalization, Einsum, Trilu, Depth/SpaceToDepth) and an extended
activation tier (Elu/Selu/Celu/Gelu/Mish/HardSigmoid/HardSwish/Shrink,
trig/hyperbolic).  Shape-like operands (Reshape/Slice/Expand/...)
must be constants/initializers — static shapes are the XLA contract.
Unsupported ops (or unsupported attribute forms) raise with the op name.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import Param, TypeConverters, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.schema import DataTable
from . import proto


def _pads_to_lax(pads: List[int], spatial: int):
    if not pads:
        return [(0, 0)] * spatial
    half = len(pads) // 2
    return [(int(pads[i]), int(pads[i + half])) for i in range(half)]


def _same_pads(in_shape, kernel, strides, lower: bool):
    """Explicit ONNX SAME_UPPER/SAME_LOWER padding pairs."""
    out = []
    for size, k, s in zip(in_shape, kernel, strides):
        total = max((-(-size // s) - 1) * s + k - size, 0)
        small, big = total // 2, total - total // 2
        out.append((big, small) if lower else (small, big))
    return out


def _conv(x, w, b, attrs):
    spatial = w.ndim - 2
    strides = tuple(attrs.get("strides", [1] * spatial))
    dil = tuple(attrs.get("dilations", [1] * spatial))
    groups = int(attrs.get("group", 1))
    auto = attrs.get("auto_pad", "NOTSET")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        eff_k = [(w.shape[2 + i] - 1) * dil[i] + 1 for i in range(spatial)]
        pads = _same_pads(x.shape[2:], eff_k, strides,
                          lower=(auto == "SAME_LOWER"))
    else:
        pads = _pads_to_lax(attrs.get("pads", []), spatial)
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCW", "OIW", "NCW")
    out = jax.lax.conv_general_dilated(
        x, w, strides, pads, rhs_dilation=dil,
        dimension_numbers=dn, feature_group_count=groups)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * spatial)
    return out


def _pool(x, attrs, reducer, init, avg=False):
    k = attrs["kernel_shape"]
    spatial = len(k)
    strides = tuple(attrs.get("strides", [1] * spatial))
    auto = attrs.get("auto_pad", "NOTSET")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        pads = _same_pads(x.shape[2:], k, strides,
                          lower=(auto == "SAME_LOWER"))
    else:
        pads = _pads_to_lax(attrs.get("pads", []), spatial)
    window = (1, 1) + tuple(k)
    strides_full = (1, 1) + strides
    pads_full = [(0, 0), (0, 0)] + pads
    out = jax.lax.reduce_window(x, init, reducer, window, strides_full,
                                pads_full)
    if avg:
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       strides_full, pads_full)
        out = out / counts
    return out


def _gemm(env, node, attrs):
    a = env[node["inputs"][0]]
    b = env[node["inputs"][1]]
    if attrs.get("transA", 0):
        a = a.T
    if attrs.get("transB", 0):
        b = b.T
    out = attrs.get("alpha", 1.0) * (a @ b)
    if len(node["inputs"]) > 2:
        out = out + attrs.get("beta", 1.0) * env[node["inputs"][2]]
    return out


def _batchnorm(x, scale, bias, mean, var, attrs):
    eps = attrs.get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean.reshape(shape)) / jnp.sqrt(
        var.reshape(shape) + eps) * scale.reshape(shape) + bias.reshape(shape)


_UNARY = {
    "Relu": jax.nn.relu, "Sigmoid": jax.nn.sigmoid, "Tanh": jnp.tanh,
    "Exp": jnp.exp, "Log": jnp.log, "Neg": jnp.negative, "Sqrt": jnp.sqrt,
    "Abs": jnp.abs, "Erf": jax.lax.erf, "Floor": jnp.floor,
    "Ceil": jnp.ceil, "Identity": lambda x: x, "Softplus": jax.nn.softplus,
    # ONNX Round is round-half-to-even, which numpy/jnp.round implements
    "Round": jnp.round, "Sign": jnp.sign,
    "Reciprocal": lambda x: 1.0 / x, "Softsign": jax.nn.soft_sign,
    "Sin": jnp.sin, "Cos": jnp.cos, "Tan": jnp.tan,
    "Asin": jnp.arcsin, "Acos": jnp.arccos, "Atan": jnp.arctan,
    "Sinh": jnp.sinh, "Cosh": jnp.cosh,
    "Asinh": jnp.arcsinh, "Acosh": jnp.arccosh, "Atanh": jnp.arctanh,
    "Not": jnp.logical_not, "IsNaN": jnp.isnan,
    "Mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "HardSwish": jax.nn.hard_swish,
}

_BINARY = {
    "Add": jnp.add, "Sub": jnp.subtract, "Mul": jnp.multiply,
    "Div": jnp.divide, "Pow": jnp.power,
    "And": jnp.logical_and, "Or": jnp.logical_or,
    "Xor": jnp.logical_xor,
    "GreaterOrEqual": jnp.greater_equal, "LessOrEqual": jnp.less_equal,
    "PRelu": lambda x, s: jnp.where(x < 0, s * x, x),
}

#: reductions sharing the axes/keepdims/noop_with_empty_axes contract
_REDUCE = {
    "ReduceSum": jnp.sum, "ReduceMax": jnp.max, "ReduceMin": jnp.min,
    "ReduceMean": jnp.mean, "ReduceProd": jnp.prod,
    "ReduceL1": lambda x, axis, keepdims: jnp.sum(
        jnp.abs(x), axis=axis, keepdims=keepdims),
    "ReduceL2": lambda x, axis, keepdims: jnp.sqrt(jnp.sum(
        jnp.square(x), axis=axis, keepdims=keepdims)),
    "ReduceSumSquare": lambda x, axis, keepdims: jnp.sum(
        jnp.square(x), axis=axis, keepdims=keepdims),
    "ReduceLogSum": lambda x, axis, keepdims: jnp.log(jnp.sum(
        x, axis=axis, keepdims=keepdims)),
    "ReduceLogSumExp": lambda x, axis, keepdims: jax.nn.logsumexp(
        x, axis=axis, keepdims=keepdims),
}


def _eval_node(node: Dict[str, Any], env: Dict[str, Any]):
    op = node["op_type"]
    attrs = node["attrs"]
    ins = node["inputs"]

    if op in _UNARY:
        return _UNARY[op](env[ins[0]])
    if op in _BINARY:
        return _BINARY[op](env[ins[0]], env[ins[1]])
    if op == "Conv":
        b = env[ins[2]] if len(ins) > 2 else None
        return _conv(env[ins[0]], env[ins[1]], b, attrs)
    if op == "Gemm":
        return _gemm(env, node, attrs)
    if op == "MatMul":
        return env[ins[0]] @ env[ins[1]]
    if op == "BatchNormalization":
        return _batchnorm(env[ins[0]], env[ins[1]], env[ins[2]],
                          env[ins[3]], env[ins[4]], attrs)
    if op == "MaxPool":
        return _pool(env[ins[0]], attrs, jax.lax.max, -jnp.inf)
    if op == "AveragePool":
        return _pool(env[ins[0]], attrs, jax.lax.add, 0.0, avg=True)
    if op == "GlobalAveragePool":
        x = env[ins[0]]
        return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)
    if op == "GlobalMaxPool":
        x = env[ins[0]]
        return jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)
    if op == "Flatten":
        ax = attrs.get("axis", 1)
        x = env[ins[0]]
        lead = int(np.prod(x.shape[:ax])) if ax else 1
        return x.reshape(lead, -1)
    if op == "Reshape":
        shape = np.asarray(env[ins[1]]).tolist()
        x = env[ins[0]]
        shape = [x.shape[i] if s == 0 else int(s)
                 for i, s in enumerate(shape)]
        return x.reshape(shape)
    if op == "Transpose":
        perm = attrs.get("perm")
        return jnp.transpose(env[ins[0]], perm)
    if op == "Concat":
        return jnp.concatenate([env[i] for i in ins],
                               axis=attrs.get("axis", 0))
    if op == "Softmax":
        return jax.nn.softmax(env[ins[0]], axis=attrs.get("axis", -1))
    if op == "LogSoftmax":
        return jax.nn.log_softmax(env[ins[0]], axis=attrs.get("axis", -1))
    if op == "LeakyRelu":
        return jax.nn.leaky_relu(env[ins[0]], attrs.get("alpha", 0.01))
    if op == "Clip":
        lo = env[ins[1]] if len(ins) > 1 and ins[1] else attrs.get(
            "min", -jnp.inf)
        hi = env[ins[2]] if len(ins) > 2 and ins[2] else attrs.get(
            "max", jnp.inf)
        return jnp.clip(env[ins[0]], lo, hi)
    if op == "Dropout":
        return env[ins[0]]   # inference mode
    if op == "Constant":
        for key in ("value", "value_float", "value_int"):
            if key in attrs:
                return jnp.asarray(attrs[key])
        raise ValueError("Constant node without value")
    if op == "ReduceMean":
        axes = attrs.get("axes")
        return jnp.mean(env[ins[0]],
                        axis=tuple(axes) if axes else None,
                        keepdims=bool(attrs.get("keepdims", 1)))
    if op == "Squeeze":
        axes = attrs.get("axes") or (
            np.asarray(env[ins[1]]).tolist() if len(ins) > 1 else None)
        return jnp.squeeze(env[ins[0]],
                           axis=tuple(axes) if axes else None)
    if op == "Unsqueeze":
        axes = attrs.get("axes") or np.asarray(env[ins[1]]).tolist()
        x = env[ins[0]]
        for ax in sorted(axes):
            x = jnp.expand_dims(x, ax)
        return x
    if op == "Cast":
        to = proto.ONNX_DTYPES.get(attrs.get("to", 1), np.float32)
        return env[ins[0]].astype(to)
    if op == "LRN":
        # local response norm across channels (NCHW axis 1)
        x = env[ins[0]]
        size = attrs.get("size", 5)
        alpha, beta, bias = (attrs.get("alpha", 1e-4),
                             attrs.get("beta", 0.75), attrs.get("bias", 1.0))
        sq = x * x
        half = size // 2
        pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2)
        den = jax.lax.reduce_window(sq, 0.0, jax.lax.add,
                                    (1, size) + (1,) * (x.ndim - 2),
                                    (1,) * x.ndim, pads)
        return x / (bias + alpha / size * den) ** beta
    if op == "Gather":
        return jnp.take(env[ins[0]],
                        jnp.asarray(env[ins[1]]).astype(jnp.int32),
                        axis=attrs.get("axis", 0))
    if op == "Shape":
        # static under jit: shapes are trace-time constants
        shp = env[ins[0]].shape
        nd = len(shp)
        st = attrs.get("start", 0)
        en = attrs.get("end", nd)
        st = st + nd if st < 0 else st
        en = en + nd if en < 0 else en
        return jnp.asarray(shp[st:en], jnp.int64)
    if op == "Slice":
        # opset >= 10 form: starts/ends[/axes/steps] are (initializer)
        # inputs — like Reshape, shape-like operands must be constants
        x = env[ins[0]]
        starts = np.asarray(env[ins[1]]).tolist()
        ends = np.asarray(env[ins[2]]).tolist()
        axes = (np.asarray(env[ins[3]]).tolist()
                if len(ins) > 3 and ins[3] else list(range(len(starts))))
        steps = (np.asarray(env[ins[4]]).tolist()
                 if len(ins) > 4 and ins[4] else [1] * len(starts))
        sl = [slice(None)] * x.ndim
        int32max = 2 ** 31 - 1
        for st, en, ax, sp in zip(starts, ends, axes, steps):
            en = None if en >= int32max else int(en)
            sl[int(ax)] = slice(int(st), en, int(sp))
        return x[tuple(sl)]
    if op == "Split":
        x = env[ins[0]]
        ax = attrs.get("axis", 0)
        if len(ins) > 1 and ins[1]:
            sizes = np.asarray(env[ins[1]]).tolist()
        elif attrs.get("split"):
            sizes = list(attrs["split"])
        else:
            # opset-18 default: ceil-sized chunks, remainder in the last
            k = len(node["outputs"])
            chunk = -(-x.shape[ax] // k)
            sizes = [chunk] * (k - 1) + [x.shape[ax] - chunk * (k - 1)]
        offs = np.cumsum([0] + sizes)
        pieces = tuple(
            jax.lax.slice_in_dim(x, int(offs[i]), int(offs[i + 1]),
                                 axis=ax)
            for i in range(len(sizes)))
        return pieces if len(pieces) > 1 else pieces[0]
    if op in _REDUCE:
        fn = _REDUCE[op]
        axes = attrs.get("axes") or (
            np.asarray(env[ins[1]]).tolist() if len(ins) > 1 and ins[1]
            else None)
        if not axes and attrs.get("noop_with_empty_axes"):
            return env[ins[0]]       # spec: empty axes + flag = identity
        return fn(env[ins[0]], axis=tuple(axes) if axes else None,
                  keepdims=bool(attrs.get("keepdims", 1)))
    if op in ("Sum", "Mean", "Max", "Min"):   # variadic elementwise
        fold = {"Max": jnp.maximum, "Min": jnp.minimum}.get(op, jnp.add)
        acc = env[ins[0]]
        for i in ins[1:]:
            acc = fold(acc, env[i])
        return acc / len(ins) if op == "Mean" else acc
    if op == "Mod":
        x, y = env[ins[0]], env[ins[1]]
        # fmod=1: C-style sign-of-dividend; default: python/numpy mod
        return jnp.fmod(x, y) if attrs.get("fmod") else jnp.mod(x, y)
    if op == "Elu":
        a = attrs.get("alpha", 1.0)
        x = env[ins[0]]
        return jnp.where(x < 0, a * (jnp.exp(x) - 1.0), x)
    if op == "Selu":
        a = attrs.get("alpha", 1.67326319217681884765625)
        g = attrs.get("gamma", 1.05070102214813232421875)
        x = env[ins[0]]
        return g * jnp.where(x <= 0, a * (jnp.exp(x) - 1.0), x)
    if op == "Celu":
        a = attrs.get("alpha", 1.0)
        x = env[ins[0]]
        return jnp.maximum(x, 0) + jnp.minimum(
            0, a * (jnp.exp(x / a) - 1.0))
    if op == "ThresholdedRelu":
        a = attrs.get("alpha", 1.0)
        x = env[ins[0]]
        return jnp.where(x > a, x, 0.0)
    if op == "HardSigmoid":
        a = attrs.get("alpha", 0.2)
        b = attrs.get("beta", 0.5)
        return jnp.clip(a * env[ins[0]] + b, 0.0, 1.0)
    if op == "Gelu":
        approx = attrs.get("approximate", b"none")
        approx = approx.decode() if isinstance(approx, bytes) else approx
        return jax.nn.gelu(env[ins[0]], approximate=approx == "tanh")
    if op == "Shrink":
        lambd = attrs.get("lambd", 0.5)
        bias = attrs.get("bias", 0.0)
        x = env[ins[0]]
        return jnp.where(x < -lambd, x + bias,
                         jnp.where(x > lambd, x - bias, 0.0))
    if op == "IsInf":
        x = env[ins[0]]
        pos = bool(attrs.get("detect_positive", 1))
        neg = bool(attrs.get("detect_negative", 1))
        out = jnp.zeros(x.shape, bool)
        if pos:
            out = out | (x == jnp.inf)
        if neg:
            out = out | (x == -jnp.inf)
        return out
    if op == "Hardmax":
        x = env[ins[0]]
        ax = attrs.get("axis", -1)
        ax = ax + x.ndim if ax < 0 else ax
        return jax.nn.one_hot(jnp.argmax(x, axis=ax), x.shape[ax],
                              axis=ax, dtype=x.dtype)
    if op == "TopK":
        x = env[ins[0]]
        k = int(np.asarray(env[ins[1]]).reshape(()).item())
        ax = attrs.get("axis", -1)
        ax = ax + x.ndim if ax < 0 else ax
        largest = bool(attrs.get("largest", 1))
        moved = jnp.moveaxis(x, ax, -1)
        vals, idx = jax.lax.top_k(moved if largest else -moved, k)
        if not largest:
            vals = -vals
        return [jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx, -1, ax).astype(jnp.int64)]
    if op == "CumSum":
        x = env[ins[0]]
        ax = int(np.asarray(env[ins[1]]).reshape(()).item())
        rev = bool(attrs.get("reverse", 0))
        if rev:
            x = jnp.flip(x, axis=ax)
        out = jnp.cumsum(x, axis=ax)
        if attrs.get("exclusive"):
            pad = [(0, 0)] * x.ndim
            pad[ax] = (1, 0)
            out = jnp.pad(out, pad)
            out = jax.lax.slice_in_dim(out, 0, x.shape[ax], axis=ax)
        return jnp.flip(out, axis=ax) if rev else out
    if op == "OneHot":
        indices = env[ins[0]]
        depth = int(np.asarray(env[ins[1]]).reshape(()).item())
        values = jnp.asarray(env[ins[2]])       # [off_value, on_value]
        ax = attrs.get("axis", -1)
        # spec: negative indices wrap once; anything outside
        # [-depth, depth-1] yields an all-off row (one_hot of -1 is 0s)
        norm = jnp.where(indices < 0, indices + depth, indices)
        valid = (norm >= 0) & (norm < depth)
        oh = jax.nn.one_hot(jnp.where(valid, norm, -1), depth, axis=ax)
        return oh * (values[1] - values[0]) + values[0]
    if op == "GatherElements":
        x = env[ins[0]]
        idx = env[ins[1]].astype(jnp.int64)
        ax = attrs.get("axis", 0)
        idx = jnp.where(idx < 0, idx + x.shape[ax], idx)
        return jnp.take_along_axis(x, idx, axis=ax)
    if op == "Einsum":
        eq = attrs.get("equation", b"")
        eq = eq.decode() if isinstance(eq, bytes) else eq
        return jnp.einsum(eq, *[env[i] for i in ins])
    if op == "Trilu":
        x = env[ins[0]]
        k = (int(np.asarray(env[ins[1]]).reshape(()).item())
             if len(ins) > 1 and ins[1] else 0)
        return (jnp.triu(x, k) if attrs.get("upper", 1)
                else jnp.tril(x, k))
    if op == "EyeLike":
        x = env[ins[0]]
        return jnp.eye(x.shape[0], x.shape[1],
                       k=attrs.get("k", 0), dtype=x.dtype)
    if op == "Size":
        return jnp.asarray(int(np.prod(env[ins[0]].shape)), jnp.int64)
    if op == "DepthToSpace":
        x = env[ins[0]]
        b, c, h, w = x.shape
        bs = attrs.get("blocksize")
        mode = attrs.get("mode", b"DCR")
        mode = mode.decode() if isinstance(mode, bytes) else mode
        if mode == "DCR":
            t = x.reshape(b, bs, bs, c // (bs * bs), h, w)
            t = t.transpose(0, 3, 4, 1, 5, 2)
        else:                                   # CRD
            t = x.reshape(b, c // (bs * bs), bs, bs, h, w)
            t = t.transpose(0, 1, 4, 2, 5, 3)
        return t.reshape(b, c // (bs * bs), h * bs, w * bs)
    if op == "SpaceToDepth":
        x = env[ins[0]]
        b, c, h, w = x.shape
        bs = attrs.get("blocksize")
        t = x.reshape(b, c, h // bs, bs, w // bs, bs)
        t = t.transpose(0, 3, 5, 1, 2, 4)
        return t.reshape(b, c * bs * bs, h // bs, w // bs)
    if op in ("ArgMax", "ArgMin"):
        fn = jnp.argmax if op == "ArgMax" else jnp.argmin
        x = env[ins[0]]
        ax = attrs.get("axis", 0)
        if attrs.get("select_last_index"):
            # last tied index = n-1 - first index over the reversed axis
            out = x.shape[ax] - 1 - fn(jnp.flip(x, axis=ax), axis=ax)
        else:
            out = fn(x, axis=ax)
        if attrs.get("keepdims", 1):
            out = jnp.expand_dims(out, ax)
        return out.astype(jnp.int64)
    if op == "Where":
        return jnp.where(env[ins[0]], env[ins[1]], env[ins[2]])
    if op in ("Equal", "Greater", "Less"):
        fn = {"Equal": jnp.equal, "Greater": jnp.greater,
              "Less": jnp.less}[op]
        return fn(env[ins[0]], env[ins[1]])
    if op == "Expand":
        shape = np.asarray(env[ins[1]]).tolist()
        x = env[ins[0]]
        # ONNX Expand follows numpy broadcasting with dim-1 stretching
        shape = list(np.broadcast_shapes(tuple(x.shape), tuple(
            int(d) for d in shape)))
        return jnp.broadcast_to(x, shape)
    if op == "Tile":
        reps = np.asarray(env[ins[1]]).tolist()
        return jnp.tile(env[ins[0]], [int(r) for r in reps])
    if op == "ConstantOfShape":
        shape = [int(d) for d in np.asarray(env[ins[0]]).tolist()]
        val = attrs.get("value")
        if val is None:
            return jnp.zeros(shape, jnp.float32)
        v = np.asarray(val).reshape(-1)[0]
        return jnp.full(shape, v, dtype=np.asarray(val).dtype)
    if op == "Range":
        start, limit, delta = (np.asarray(env[i]).reshape(()).item()
                               for i in ins[:3])
        return jnp.arange(start, limit, delta)
    if op == "Pad":
        x = env[ins[0]]
        pads = (np.asarray(env[ins[1]]).tolist() if len(ins) > 1
                else list(attrs.get("pads", [])))
        cval = (np.asarray(env[ins[2]]).reshape(()).item()
                if len(ins) > 2 and ins[2] else attrs.get("value", 0.0))
        mode = attrs.get("mode", b"constant")
        mode = mode.decode() if isinstance(mode, bytes) else mode
        pairs = _pads_to_lax(pads, x.ndim)   # per-listed-axis (beg, end)
        if len(ins) > 3 and ins[3]:
            # opset-18 axes input: pads are ordered per the axes list
            axes = [int(a) + (x.ndim if a < 0 else 0)
                    for a in np.asarray(env[ins[3]]).tolist()]
            widths = [(0, 0)] * x.ndim
            for a, pr in zip(axes, pairs):
                widths[a] = pr
        else:
            widths = pairs
        if mode == "constant":
            return jnp.pad(x, widths, constant_values=cval)
        return jnp.pad(x, widths,
                       mode={"reflect": "reflect", "edge": "edge"}[mode])
    if op == "LayerNormalization":
        x = env[ins[0]]
        ax = attrs.get("axis", -1)
        ax = ax + x.ndim if ax < 0 else ax
        # spec: normalize over ALL axes [axis, rank) jointly
        axes = tuple(range(ax, x.ndim))
        eps = attrs.get("epsilon", 1e-5)
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        out = (x - mean) / jnp.sqrt(var + eps) * env[ins[1]]
        if len(ins) > 2 and ins[2]:
            out = out + env[ins[2]]
        return out
    raise NotImplementedError(
        f"ONNX op {op!r} is not supported yet "
        f"(node {node['name'] or '<unnamed>'})")


class OnnxGraph:
    """Parsed + converted ONNX graph, callable as a jax function."""

    def __init__(self, model_bytes: bytes):
        parsed = proto.parse_model(model_bytes)
        self.graph = parsed["graph"]
        self.weights = {k: jnp.asarray(v)
                        for k, v in self.graph["initializers"].items()}
        init_names = set(self.graph["initializers"])
        self.input_names = [v["name"] for v in self.graph["inputs"]
                            if v["name"] not in init_names]
        self.output_names = [v["name"] for v in self.graph["outputs"]]
        self.input_shapes = {v["name"]: v["shape"]
                             for v in self.graph["inputs"]}

    def __call__(self, *inputs):
        env: Dict[str, Any] = dict(self.weights)
        env[""] = None
        for name, val in zip(self.input_names, inputs):
            env[name] = val
        for node in self.graph["nodes"]:
            outs = node["outputs"]
            result = _eval_node(node, env)
            if isinstance(result, (tuple, list)):  # multi-output op
                # (Split, TopK, ...)
                for o, r in zip(outs, result):
                    env[o] = r
            elif len(outs) == 1:
                env[outs[0]] = result
            else:  # e.g. Dropout with mask output
                env[outs[0]] = result
                for o in outs[1:]:
                    env[o] = None
        results = [env[o] for o in self.output_names]
        return results[0] if len(results) == 1 else tuple(results)


class ONNXModel(Transformer, HasInputCol, HasOutputCol):
    """DataFrame transformer scoring an ONNX model on the TPU.

    API parity with the reference: setModelLocation/setModelPayload,
    miniBatchSize, softMaxDict-style post-ops are left to pipeline stages.
    """

    miniBatchSize = Param("miniBatchSize", "Rows per device minibatch",
                          default=64, typeConverter=TypeConverters.toInt)
    modelLocation = Param("modelLocation", "Path to the .onnx file",
                          default=None, typeConverter=TypeConverters.toString)

    def __init__(self, model_bytes: Optional[bytes] = None, **kwargs):
        super().__init__(**kwargs)
        self._graph: Optional[OnnxGraph] = None
        self._jitted = None
        if model_bytes is not None:
            self.setModelPayload(model_bytes)
        elif self.getModelLocation():
            self._load_location()

    def setModelPayload(self, model_bytes: bytes) -> "ONNXModel":
        self._model_bytes = model_bytes
        self._graph = OnnxGraph(model_bytes)
        self._jitted = jax.jit(self._graph)
        return self

    def setModelLocation(self, path: str) -> "ONNXModel":
        self.set("modelLocation", path)
        self._load_location()
        return self

    def _load_location(self):
        with open(self.getModelLocation(), "rb") as fh:
            self.setModelPayload(fh.read())

    def getModelInputs(self):
        return {n: self._graph.input_shapes.get(n)
                for n in self._graph.input_names}

    def getModelOutputs(self):
        return list(self._graph.output_names)

    def _transform(self, table: DataTable) -> DataTable:
        if self._graph is None:
            raise ValueError("ONNXModel has no model; call "
                             "setModelLocation() or setModelPayload()")
        col = table[self.getInputCol()]
        if col.dtype == object:
            col = np.stack([np.asarray(r, np.float32) for r in col])
        col = np.asarray(col, np.float32)
        # reshape flat vectors to the model's input shape when known
        shape = self._graph.input_shapes.get(self._graph.input_names[0])
        if shape and len(shape) > 2 and col.ndim == 2:
            tail = [d for d in shape[1:]]
            if all(d > 0 for d in tail) and int(np.prod(tail)) == col.shape[1]:
                col = col.reshape((-1, *tail))
        bs = self.getMiniBatchSize()
        outs = []
        for start in range(0, col.shape[0], bs):
            batch = col[start:start + bs]
            pad = bs - batch.shape[0]
            if pad:
                batch = np.concatenate(
                    [batch, np.zeros((pad,) + batch.shape[1:], batch.dtype)])
            out = self._jitted(jnp.asarray(batch))
            if isinstance(out, tuple):
                out = out[0]
            out = np.asarray(out)
            outs.append(out[:bs - pad] if pad else out)
        result = np.concatenate(outs, axis=0)
        if result.ndim > 2:
            result = result.reshape(result.shape[0], -1)
        return table.withColumn(self.getOutputCol(),
                                result.astype(np.float64))

    def _save_extra(self, path: str) -> None:
        import os
        with open(os.path.join(path, "model.onnx"), "wb") as f:
            f.write(self._model_bytes)

    def _load_extra(self, path: str) -> None:
        import os
        with open(os.path.join(path, "model.onnx"), "rb") as f:
            self.setModelPayload(f.read())
