from .model import ONNXModel, OnnxGraph
from . import proto

__all__ = ["ONNXModel", "OnnxGraph", "proto"]
