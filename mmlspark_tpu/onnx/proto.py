"""Minimal protobuf wire-format codec for ONNX model files.

The reference scores ONNX models through onnxruntime JNI
(onnx/ONNXModel.scala, expected path, UNVERIFIED; SURVEY.md §2.1).  This
environment has neither onnxruntime nor the ``onnx`` python package, so this
module implements the small slice of protobuf needed to read (and write)
ONNX ``ModelProto`` files directly: varints, length-delimited fields, packed
repeated scalars — nothing more.  The decoder is schema-driven over the ONNX
message layout; the encoder exists to build test fixtures and to export
simple graphs.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Tuple

import numpy as np

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(data: memoryview) -> Iterator[Tuple[int, int, Any]]:
    pos, end = 0, len(data)
    while pos < end:
        key, pos = _read_varint(data, pos)
        field, wt = key >> 3, key & 7
        if wt == _VARINT:
            val, pos = _read_varint(data, pos)
        elif wt == _I64:
            val = bytes(data[pos:pos + 8])
            pos += 8
        elif wt == _LEN:
            ln, pos = _read_varint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        elif wt == _I32:
            val = bytes(data[pos:pos + 4])
            pos += 4
        else:
            raise ValueError(f"Unsupported wire type {wt}")
        yield field, wt, val


def parse(data) -> Dict[int, List[Any]]:
    """Parse one message into {field_number: [raw values...]}."""
    out: Dict[int, List[Any]] = {}
    for field, _, val in _iter_fields(memoryview(data)):
        out.setdefault(field, []).append(val)
    return out


def as_str(v) -> str:
    return bytes(v).decode("utf-8")


def packed_varints(vals: List[Any]) -> List[int]:
    """Repeated int64 field: packed bytes and/or individual varints."""
    out: List[int] = []
    for v in vals:
        if isinstance(v, int):
            out.append(v)
        else:
            mv = memoryview(v)
            pos = 0
            while pos < len(mv):
                x, pos = _read_varint(mv, pos)
                out.append(x)
    return [_signed64(x) for x in out]


def _signed64(x: int) -> int:
    return x - (1 << 64) if x >= (1 << 63) else x


def packed_floats(vals: List[Any]) -> np.ndarray:
    parts = []
    for v in vals:
        if isinstance(v, bytes) and len(v) == 4:
            parts.append(np.frombuffer(v, "<f4"))
        else:
            parts.append(np.frombuffer(bytes(v), "<f4"))
    return np.concatenate(parts) if parts else np.zeros(0, np.float32)


# -- ONNX message readers ----------------------------------------------------

ONNX_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
               7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}


def tensor_to_array(raw) -> Tuple[str, np.ndarray]:
    """TensorProto -> (name, ndarray)."""
    f = parse(raw)
    dims = packed_varints(f.get(1, []))
    dtype = ONNX_DTYPES.get(f.get(2, [1])[0], np.float32)
    name = as_str(f[8][0]) if 8 in f else ""
    if 9 in f:  # raw_data
        arr = np.frombuffer(bytes(f[9][0]), dtype=dtype)
    elif 4 in f:  # float_data
        arr = packed_floats(f[4])
    elif 7 in f:  # int64_data
        arr = np.asarray(packed_varints(f[7]), np.int64)
    elif 5 in f:  # int32_data
        arr = np.asarray(packed_varints(f[5]), np.int32).astype(dtype)
    else:
        arr = np.zeros(0, dtype)
    return name, arr.reshape(dims) if dims else arr


def parse_attribute(raw) -> Tuple[str, Any]:
    f = parse(raw)
    name = as_str(f[1][0])
    atype = f.get(20, [0])[0]
    if atype == 1:    # FLOAT
        return name, struct.unpack("<f", bytes(f[2][0]))[0]
    if atype == 2:    # INT
        return name, _signed64(f[3][0])
    if atype == 3:    # STRING
        return name, as_str(f[4][0])
    if atype == 4:    # TENSOR
        return name, tensor_to_array(f[5][0])[1]
    if atype == 6:    # FLOATS
        return name, list(packed_floats(f.get(7, [])))
    if atype == 7:    # INTS
        return name, packed_varints(f.get(8, []))
    if atype == 8:    # STRINGS
        return name, [as_str(s) for s in f.get(9, [])]
    # fall back on whichever single field is present
    for fid, conv in ((3, lambda v: _signed64(v[0])),
                      (2, lambda v: struct.unpack("<f", bytes(v[0]))[0]),
                      (4, lambda v: as_str(v[0]))):
        if fid in f:
            return name, conv(f[fid])
    return name, None


def parse_node(raw) -> Dict[str, Any]:
    f = parse(raw)
    return {
        "inputs": [as_str(v) for v in f.get(1, [])],
        "outputs": [as_str(v) for v in f.get(2, [])],
        "name": as_str(f[3][0]) if 3 in f else "",
        "op_type": as_str(f[4][0]) if 4 in f else "",
        "attrs": dict(parse_attribute(a) for a in f.get(5, [])),
    }


def parse_value_info(raw) -> Dict[str, Any]:
    f = parse(raw)
    name = as_str(f[1][0]) if 1 in f else ""
    shape, elem = [], 1
    if 2 in f:
        t = parse(f[2][0])
        if 1 in t:  # tensor_type
            tt = parse(t[1][0])
            elem = tt.get(1, [1])[0]
            if 2 in tt:
                sh = parse(tt[2][0])
                for d in sh.get(1, []):
                    dd = parse(d)
                    shape.append(dd[1][0] if 1 in dd else -1)
    return {"name": name, "shape": shape, "elem_type": elem}


def parse_model(data: bytes) -> Dict[str, Any]:
    """ModelProto -> {graph: {nodes, initializers, inputs, outputs}}."""
    m = parse(data)
    if 7 not in m:
        raise ValueError("Not an ONNX ModelProto (no graph field)")
    g = parse(m[7][0])
    initializers = dict(tensor_to_array(t) for t in g.get(5, []))
    return {
        "ir_version": m.get(1, [0])[0],
        "graph": {
            "name": as_str(g[2][0]) if 2 in g else "",
            "nodes": [parse_node(n) for n in g.get(1, [])],
            "initializers": initializers,
            "inputs": [parse_value_info(v) for v in g.get(11, [])],
            "outputs": [parse_value_info(v) for v in g.get(12, [])],
        },
    }


# -- minimal encoder (test fixtures + simple graph export) -------------------

def _varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field(num: int, wt: int, payload: bytes) -> bytes:
    return _varint((num << 3) | wt) + payload


def _len_field(num: int, payload: bytes) -> bytes:
    return _field(num, _LEN, _varint(len(payload)) + payload)


def encode_tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    dt = {np.dtype(np.float32): 1, np.dtype(np.int64): 7,
          np.dtype(np.float64): 11, np.dtype(np.int32): 6}[arr.dtype]
    out = b""
    for d in arr.shape:
        out += _field(1, _VARINT, _varint(d))
    out += _field(2, _VARINT, _varint(dt))
    out += _len_field(8, name.encode())
    out += _len_field(9, arr.tobytes())
    return out


def encode_attr(name: str, value) -> bytes:
    out = _len_field(1, name.encode())
    if isinstance(value, float):
        out += _field(2, _I32, struct.pack("<f", value))
        out += _field(20, _VARINT, _varint(1))
    elif isinstance(value, (bool, int, np.integer)):
        out += _field(3, _VARINT, _varint(int(value) & ((1 << 64) - 1)))
        out += _field(20, _VARINT, _varint(2))
    elif isinstance(value, str):
        out += _len_field(4, value.encode())
        out += _field(20, _VARINT, _varint(3))
    elif isinstance(value, np.ndarray):
        out += _len_field(5, encode_tensor("", value))
        out += _field(20, _VARINT, _varint(4))
    elif isinstance(value, (list, tuple)) and value and isinstance(
            value[0], float):
        out += _len_field(7, b"".join(struct.pack("<f", v) for v in value))
        out += _field(20, _VARINT, _varint(6))
    elif isinstance(value, (list, tuple)):
        out += _len_field(8, b"".join(
            _varint(int(v) & ((1 << 64) - 1)) for v in value))
        out += _field(20, _VARINT, _varint(7))
    else:
        raise TypeError(f"Unsupported attribute {name}={value!r}")
    return out


def encode_node(op_type: str, inputs, outputs, **attrs) -> bytes:
    out = b"".join(_len_field(1, i.encode()) for i in inputs)
    out += b"".join(_len_field(2, o.encode()) for o in outputs)
    out += _len_field(4, op_type.encode())
    out += b"".join(_len_field(5, encode_attr(k, v))
                    for k, v in attrs.items())
    return out


def encode_value_info(name: str, shape, elem_type: int = 1) -> bytes:
    dims = b"".join(_len_field(1, _field(1, _VARINT, _varint(d)))
                    for d in shape)
    tensor_type = _field(1, _VARINT, _varint(elem_type)) + _len_field(2, dims)
    type_proto = _len_field(1, tensor_type)
    return _len_field(1, name.encode()) + _len_field(2, type_proto)


def encode_model(nodes: List[bytes], initializers: Dict[str, np.ndarray],
                 inputs: List[Tuple[str, List[int]]],
                 outputs: List[Tuple[str, List[int]]],
                 name: str = "g") -> bytes:
    g = b"".join(_len_field(1, n) for n in nodes)
    g += _len_field(2, name.encode())
    g += b"".join(_len_field(5, encode_tensor(k, v))
                  for k, v in initializers.items())
    g += b"".join(_len_field(11, encode_value_info(n, s))
                  for n, s in inputs)
    g += b"".join(_len_field(12, encode_value_info(n, s))
                  for n, s in outputs)
    model = _field(1, _VARINT, _varint(8))        # ir_version
    model += _len_field(7, g)
    # opset_import { version = 17 }
    model += _len_field(8, _len_field(1, b"") + _field(2, _VARINT,
                                                      _varint(17)))
    return model
