"""Simplified ML training + evaluation (reference ``train/`` package).

Reference: src/main/scala/com/microsoft/ml/spark/train/ (expected path,
UNVERIFIED — SURVEY.md §2.1): TrainClassifier/TrainRegressor wrap any
learner together with auto-featurization into one estimator;
ComputeModelStatistics / ComputePerInstanceStatistics compute evaluation
metrics *as pipeline transformers* (observability-as-a-stage, SURVEY.md §5.5).
"""

from .train import (
    TrainClassifier,
    TrainedClassifierModel,
    TrainRegressor,
    TrainedRegressorModel,
)
from .metrics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
)

__all__ = [
    "TrainClassifier", "TrainedClassifierModel",
    "TrainRegressor", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
]
