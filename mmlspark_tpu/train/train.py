"""TrainClassifier / TrainRegressor.

Reference: train/TrainClassifier.scala, train/TrainRegressor.scala (expected
paths, UNVERIFIED — SURVEY.md §2.1).  Wraps any learner plus automatic
featurization (Featurize over every non-label column) into a single
estimator, so ``TrainClassifier(model=LightGBMClassifier(), labelCol="y")``
fits on a raw mixed-type table with no manual vector assembly.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..core.params import (HasFeaturesCol, HasLabelCol, Param,
                           TypeConverters)
from ..core.pipeline import Estimator, Model, PipelineStage, Transformer
from ..core.schema import DataTable
from ..core import serialize
from ..featurize import Featurize, ValueIndexer


class _TrainParams(HasLabelCol, HasFeaturesCol):
    numFeatures = Param("numFeatures",
                        "Hash dimension for high-cardinality text columns",
                        default=262144, typeConverter=TypeConverters.toInt)


class _TrainBase(_TrainParams, Estimator):
    __abstractstage__ = True
    _reindex_label = False

    def __init__(self, model: Optional[Estimator] = None, **kwargs):
        super().__init__(**kwargs)
        self._model = model

    def getModel(self) -> Optional[Estimator]:
        return self._model

    def setModel(self, model: Estimator) -> "_TrainBase":
        self._model = model
        return self

    def _fit(self, table: DataTable) -> "_TrainedModel":
        if self._model is None:
            raise ValueError(
                f"{type(self).__name__} needs an inner learner; pass "
                "model=<estimator> (e.g. LightGBMClassifier())")
        label = self.getLabelCol()
        feat_col = self.getFeaturesCol()

        label_model = None
        if self._reindex_label and table[label].dtype.kind not in "fiub":
            label_model = ValueIndexer(
                inputCol=label, outputCol=label).fit(table)
            table = label_model._transform(table)

        feature_cols = [c for c in table.columns
                        if c != label and c != feat_col]
        featurizer = None
        if feat_col not in table:
            featurizer = Featurize(
                inputCols=feature_cols, outputCol=feat_col,
                numFeatures=self.getNumFeatures()).fit(table)
            table = featurizer._transform(table)

        inner = self._model.copy()
        for p, v in (("featuresCol", feat_col), ("labelCol", label)):
            if inner.hasParam(p):
                inner.set(p, v)
        fitted = inner._fit(table)

        model = self._model_cls(featurizer=featurizer,
                                label_model=label_model, fitted=fitted)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model

    # unfitted estimator persistence: the wrapped learner is real state
    def _save_extra(self, path: str) -> None:
        serialize.save_optional_stage(path, "model", self._model)

    def _load_extra(self, path: str) -> None:
        self._model = serialize.load_optional_stage(path, "model")


class _TrainedModel(_TrainParams, Model):
    __abstractstage__ = True

    def __init__(self, featurizer=None, label_model=None, fitted=None,
                 **kwargs):
        super().__init__(**kwargs)
        self._featurizer = featurizer
        self._label_model = label_model
        self._fitted = fitted

    def getLastStage(self) -> Transformer:
        """The fitted inner model (reference naming for the wrapped stage)."""
        return self._fitted

    def _transform(self, table: DataTable) -> DataTable:
        feat_col = self.getFeaturesCol()
        if self._featurizer is not None and feat_col not in table:
            table = self._featurizer._transform(table)
        return self._fitted._transform(table)

    def _save_extra(self, path: str) -> None:
        parts = {"fitted": self._fitted}
        if self._featurizer is not None:
            parts["featurizer"] = self._featurizer
        if self._label_model is not None:
            parts["label_model"] = self._label_model
        serialize.save_json(path, "parts", sorted(parts))
        for name, stage in parts.items():
            serialize.save_stage(stage, os.path.join(path, name),
                                 overwrite=True)

    def _load_extra(self, path: str) -> None:
        names = serialize.load_json(path, "parts")
        self._featurizer = self._label_model = self._fitted = None
        for name in names:
            stage = serialize.load_stage(os.path.join(path, name))
            setattr(self, {"fitted": "_fitted",
                           "featurizer": "_featurizer",
                           "label_model": "_label_model"}[name], stage)


class TrainedClassifierModel(_TrainedModel):
    def getLevels(self):
        return self._label_model.levels if self._label_model else None


class TrainedRegressorModel(_TrainedModel):
    pass


class TrainClassifier(_TrainBase):
    """Auto-featurizing classification wrapper (train/TrainClassifier.scala)."""
    _model_cls = TrainedClassifierModel
    _reindex_label = True


class TrainRegressor(_TrainBase):
    """Auto-featurizing regression wrapper (train/TrainRegressor.scala)."""
    _model_cls = TrainedRegressorModel
