"""Evaluation metrics as pipeline transformers.

Reference: train/ComputeModelStatistics.scala,
train/ComputePerInstanceStatistics.scala (expected paths, UNVERIFIED —
SURVEY.md §2.1, §5.5).  ``ComputeModelStatistics.transform`` returns a
one-row table of metrics (classification: accuracy/precision/recall/AUC +
confusion matrix; regression: MSE/RMSE/R²/MAE); the per-instance variant
appends a per-row loss column.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..core.params import (HasLabelCol, HasPredictionCol, Param,
                           TypeConverters)
from ..core.pipeline import Transformer
from ..core.schema import DataTable

_METRIC_CHOICES = ("classification", "regression", "all", "auc", "accuracy",
                   "precision", "recall", "mse", "rmse", "r2", "mae")


def roc_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Trapezoidal AUC via rank statistics (ties handled by midranks)."""
    labels = np.asarray(labels, dtype=np.float64)
    scores = np.asarray(scores, dtype=np.float64)
    pos = labels > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(len(scores))
    sorted_scores = scores[order]
    # midranks for ties
    i = 0
    while i < len(scores):
        j = i
        while j + 1 < len(scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))


class _MetricParams(HasLabelCol, HasPredictionCol):
    scoresCol = Param("scoresCol",
                      "Probability/score column for AUC (optional)",
                      default="probability",
                      typeConverter=TypeConverters.toString)
    evaluationMetric = Param("evaluationMetric",
                             "Metric set: classification|regression|all or a "
                             "single metric name",
                             default="all",
                             typeConverter=TypeConverters.toString,
                             validator=lambda v: v in _METRIC_CHOICES)


class ComputeModelStatistics(_MetricParams, Transformer):
    """Dataset-level metrics as a one-row output table."""

    def _classification(self, table: DataTable) -> Dict[str, float]:
        y = np.asarray(table[self.getLabelCol()], dtype=np.float64)
        pred = np.asarray(table[self.getPredictionCol()], dtype=np.float64)
        classes = np.unique(np.concatenate([y, pred]))
        k = len(classes)
        yi = np.searchsorted(classes, y)
        pi = np.searchsorted(classes, pred)
        conf = np.zeros((k, k), dtype=np.int64)
        for t, p in zip(yi, pi):
            conf[t, p] += 1
        out: Dict[str, float] = {
            "accuracy": float((y == pred).mean()) if len(y) else float("nan")}
        if k == 2:
            tp, fp = conf[1, 1], conf[0, 1]
            fn = conf[1, 0]
            out["precision"] = float(tp / (tp + fp)) if tp + fp else 0.0
            out["recall"] = float(tp / (tp + fn)) if tp + fn else 0.0
        else:  # macro average
            precisions, recalls = [], []
            for c in range(k):
                tp = conf[c, c]
                fp = conf[:, c].sum() - tp
                fn = conf[c, :].sum() - tp
                precisions.append(tp / (tp + fp) if tp + fp else 0.0)
                recalls.append(tp / (tp + fn) if tp + fn else 0.0)
            out["precision"] = float(np.mean(precisions)) if k else 0.0
            out["recall"] = float(np.mean(recalls)) if k else 0.0
        scores_col = self.getScoresCol()
        if scores_col in table and k == 2:
            s = np.asarray(table[scores_col], dtype=np.float64)
            if s.ndim == 2:
                s = s[:, -1]
            out["AUC"] = roc_auc(y, s)
        self._confusion = conf
        return out

    def _regression(self, table: DataTable) -> Dict[str, float]:
        y = np.asarray(table[self.getLabelCol()], dtype=np.float64)
        pred = np.asarray(table[self.getPredictionCol()], dtype=np.float64)
        err = y - pred
        mse = float(np.mean(err ** 2))
        ss_tot = float(np.sum((y - y.mean()) ** 2))
        return {
            "mean_squared_error": mse,
            "root_mean_squared_error": float(np.sqrt(mse)),
            "mean_absolute_error": float(np.mean(np.abs(err))),
            "R^2": float(1.0 - np.sum(err ** 2) / ss_tot) if ss_tot
            else float("nan"),
        }

    def _transform(self, table: DataTable) -> DataTable:
        metric = self.getEvaluationMetric()
        self._confusion = None
        if metric in ("classification", "auc", "accuracy", "precision",
                      "recall"):
            stats = self._classification(table)
        elif metric in ("regression", "mse", "rmse", "r2", "mae"):
            stats = self._regression(table)
        else:  # "all": sniff — integer-ish labels + prediction => classification
            y = np.asarray(table[self.getLabelCol()], dtype=np.float64)
            pred = np.asarray(table[self.getPredictionCol()],
                              dtype=np.float64)
            is_cls = (np.allclose(y, np.round(y))
                      and np.allclose(pred, np.round(pred))
                      and len(np.unique(y)) <= 100)
            stats = self._classification(table) if is_cls \
                else self._regression(table)
        return DataTable({k: np.asarray([v]) for k, v in stats.items()})

    @property
    def confusionMatrix(self) -> np.ndarray:
        """Confusion matrix from the last classification transform."""
        if getattr(self, "_confusion", None) is None:
            raise ValueError("No classification transform has run yet")
        return self._confusion.copy()


class ComputePerInstanceStatistics(_MetricParams, Transformer):
    """Appends a per-row loss column (log-loss / squared error)."""

    def _transform(self, table: DataTable) -> DataTable:
        y = np.asarray(table[self.getLabelCol()], dtype=np.float64)
        scores_col = self.getScoresCol()
        if scores_col in table:
            p = np.asarray(table[scores_col], dtype=np.float64)
            eps = 1e-15
            if p.ndim == 2:  # probability vector: pick the true class
                idx = np.clip(y.astype(np.int64), 0, p.shape[1] - 1)
                p_true = p[np.arange(len(y)), idx]
            else:
                p_true = np.where(y > 0.5, p, 1.0 - p)
            loss = -np.log(np.clip(p_true, eps, 1.0))
            return table.withColumn("log_loss", loss)
        pred = np.asarray(table[self.getPredictionCol()], dtype=np.float64)
        return table.withColumn("squared_error", (y - pred) ** 2)
