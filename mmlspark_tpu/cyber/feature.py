"""Per-tenant feature engineering for access logs.

Reference: src/main/python/mmlspark/cyber/feature/{indexers,scalers}.py
(expected paths, UNVERIFIED — SURVEY.md §2.1).  The reference expresses
these as PySpark window functions partitioned by a tenant column; here
each fitted model is a plain per-tenant dict of numpy state, applied
vectorized.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..core import serialize
from ..core.params import (HasInputCol, HasOutputCol, Param,
                           Params, TypeConverters)
from ..core.pipeline import Estimator, Model
from ..core.schema import DataTable


class _HasPartitionKey(Params):
    partitionKey = Param("partitionKey",
                         "Tenant/partition column; statistics and ids are "
                         "computed independently per distinct value",
                         default="tenant",
                         typeConverter=TypeConverters.toString)

    def getPartitionKey(self) -> str:
        return self.getOrDefault("partitionKey")


class IdIndexer(_HasPartitionKey, HasInputCol, HasOutputCol, Estimator):
    """Maps arbitrary ids to contiguous 1-based indices PER TENANT (the
    reference's IdIndexer: per-partition indexing feeds the per-tenant
    latent-factor model; 0 is reserved for unseen)."""

    def _fit(self, table: DataTable) -> "IdIndexerModel":
        tenants = np.asarray(table[self.getPartitionKey()])
        ids = np.asarray(table[self.getInputCol()])
        mapping: Dict = {}
        for t in np.unique(tenants):
            vals = ids[tenants == t]
            uniq = np.unique(vals)
            mapping[t] = {v: i + 1 for i, v in enumerate(uniq)}
        m = IdIndexerModel(mapping=mapping)
        return m.setParams(**{k: v for k, v in self._iterSetParams()
                              if m.hasParam(k)})


class IdIndexerModel(_HasPartitionKey, HasInputCol, HasOutputCol, Model):
    def __init__(self, mapping=None, **kwargs):
        super().__init__(**kwargs)
        self._mapping = mapping or {}

    def vocab_size(self, tenant) -> int:
        return len(self._mapping.get(tenant, {}))

    def _transform(self, table: DataTable) -> DataTable:
        tenants = np.asarray(table[self.getPartitionKey()])
        ids = np.asarray(table[self.getInputCol()])
        out = np.zeros(len(ids), np.int64)     # unseen -> 0
        for t, m in self._mapping.items():
            mask = tenants == t
            out[mask] = np.asarray([m.get(v, 0) for v in ids[mask]])
        return table.withColumns({self.getOutputCol(): out})

    def _save_extra(self, path: str) -> None:
        serialize.save_json(path, "mapping", {
            str(t): {str(k): int(v) for k, v in m.items()}
            for t, m in self._mapping.items()})
        t0 = next(iter(self._mapping), None)
        k0 = next(iter(self._mapping[t0]), None) if t0 is not None else None
        serialize.save_json(path, "key_kinds", {
            "tenant_is_int": bool(isinstance(t0, (int, np.integer))),
            "id_is_int": bool(isinstance(k0, (int, np.integer)))})

    def _load_extra(self, path: str) -> None:
        raw = serialize.load_json(path, "mapping")
        kinds = serialize.load_json(path, "key_kinds")
        tc = int if kinds["tenant_is_int"] else str
        ic = int if kinds["id_is_int"] else str
        self._mapping = {tc(t): {ic(k): v for k, v in m.items()}
                         for t, m in raw.items()}


class _ScalerBase(_HasPartitionKey, HasInputCol, HasOutputCol, Estimator):
    def _stats(self, table: DataTable):
        tenants = np.asarray(table[self.getPartitionKey()])
        x = np.asarray(table[self.getInputCol()], np.float64)
        return tenants, x


class StandardScalarScaler(_ScalerBase):
    """Per-tenant z-score of a scalar column (reference
    StandardScalarScaler)."""

    useStd = Param("useStd", "Divide by the per-tenant std",
                   default=True, typeConverter=TypeConverters.toBool)

    def _fit(self, table: DataTable) -> "StandardScalarScalerModel":
        tenants, x = self._stats(table)
        stats = {}
        for t in np.unique(tenants):
            v = x[tenants == t]
            std = float(v.std()) if self.getOrDefault("useStd") else 1.0
            stats[t] = (float(v.mean()), std if std > 0 else 1.0)
        m = StandardScalarScalerModel(stats=stats)
        return m.setParams(**{k: v for k, v in self._iterSetParams()
                              if m.hasParam(k)})


class LinearScalarScaler(_ScalerBase):
    """Per-tenant min-max mapping to [minRequiredValue, maxRequiredValue]
    (reference LinearScalarScaler)."""

    minRequiredValue = Param("minRequiredValue", "Target minimum",
                             default=0.0,
                             typeConverter=TypeConverters.toFloat)
    maxRequiredValue = Param("maxRequiredValue", "Target maximum",
                             default=1.0,
                             typeConverter=TypeConverters.toFloat)

    def _fit(self, table: DataTable) -> "LinearScalarScalerModel":
        tenants, x = self._stats(table)
        lo, hi = (self.getOrDefault("minRequiredValue"),
                  self.getOrDefault("maxRequiredValue"))
        stats = {}
        for t in np.unique(tenants):
            v = x[tenants == t]
            vmin, vmax = float(v.min()), float(v.max())
            span = vmax - vmin
            # degenerate tenant (constant column) maps to the midpoint
            scale = (hi - lo) / span if span > 0 else 0.0
            shift = lo - vmin * scale if span > 0 else (lo + hi) / 2.0
            stats[t] = (scale, shift)
        m = LinearScalarScalerModel(stats=stats)
        return m.setParams(**{k: v for k, v in self._iterSetParams()
                              if m.hasParam(k)})


class _ScalerModelBase(_HasPartitionKey, HasInputCol, HasOutputCol, Model):
    def __init__(self, stats=None, **kwargs):
        super().__init__(**kwargs)
        self._stats = stats or {}

    def _save_extra(self, path: str) -> None:
        t0 = next(iter(self._stats), None)
        serialize.save_json(path, "stats", {
            str(t): list(v) for t, v in self._stats.items()})
        serialize.save_json(path, "key_kinds", {
            "tenant_is_int": bool(isinstance(t0, (int, np.integer)))})

    def _load_extra(self, path: str) -> None:
        raw = serialize.load_json(path, "stats")
        tc = (int if serialize.load_json(path, "key_kinds")["tenant_is_int"]
              else str)
        self._stats = {tc(t): tuple(v) for t, v in raw.items()}


class StandardScalarScalerModel(_ScalerModelBase):
    def _transform(self, table: DataTable) -> DataTable:
        tenants = np.asarray(table[self.getPartitionKey()])
        x = np.asarray(table[self.getInputCol()], np.float64)
        out = np.zeros_like(x)
        for t, (mu, sd) in self._stats.items():
            m = tenants == t
            out[m] = (x[m] - mu) / sd
        return table.withColumns({self.getOutputCol(): out})


class LinearScalarScalerModel(_ScalerModelBase):
    def _transform(self, table: DataTable) -> DataTable:
        tenants = np.asarray(table[self.getPartitionKey()])
        x = np.asarray(table[self.getInputCol()], np.float64)
        out = np.zeros_like(x)
        for t, (scale, shift) in self._stats.items():
            m = tenants == t
            out[m] = x[m] * scale + shift
        return table.withColumns({self.getOutputCol(): out})
