"""Access-anomaly detection via per-tenant collaborative filtering.

Reference: src/main/python/mmlspark/cyber/anomaly/
collaborative_filtering.py (expected path, UNVERIFIED — SURVEY.md §2.1
"Hand-written Python" row): users and resources of each tenant get
latent factors fit on observed accesses plus sampled complement
(never-accessed) pairs; an access whose predicted affinity is LOW for
its tenant is anomalous, and scores are standardized per tenant so a
fitted model emits ~N(0, 1) with high = anomalous.

TPU-first redesign: the reference runs Spark ALS; here each tenant's
factors come from dense blocked ALS — alternating ridge solves
``U = Y V (VᵀV + λI)⁻¹`` — which is two matmuls and a Cholesky solve
per side per sweep, batched over tenants by padding to the largest
tenant and ``vmap``ing.  That keeps every FLOP on the MXU; the access
matrix is binarized dense (uint users × resources per tenant), the
right shape for the single-digit-thousands entity counts this component
targets (the reference's own demo scale).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import serialize
from ..core.params import Param, Params, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import DataTable


class _HasAccessCols(Params):
    tenantCol = Param("tenantCol", "Tenant/partition column",
                      default="tenant", typeConverter=TypeConverters.toString)
    userCol = Param("userCol", "User id column", default="user",
                    typeConverter=TypeConverters.toString)
    resCol = Param("resCol", "Resource id column", default="res",
                   typeConverter=TypeConverters.toString)

    def getTenantCol(self) -> str:
        return self.getOrDefault("tenantCol")

    def getUserCol(self) -> str:
        return self.getOrDefault("userCol")

    def getResCol(self) -> str:
        return self.getOrDefault("resCol")


class ComplementAccessTransformer(_HasAccessCols, Transformer):
    """Samples (tenant, user, res) pairs ABSENT from the input access set
    — the negative examples the anomaly model trains on (reference
    ComplementAccessTransformer; factor × observed rows are drawn
    uniformly from each tenant's unseen user×res grid)."""

    complementsetFactor = Param("complementsetFactor",
                                "Complement rows per observed row",
                                default=2,
                                typeConverter=TypeConverters.toInt)
    seed = Param("seed", "Sampling seed", default=0,
                 typeConverter=TypeConverters.toInt)

    def _transform(self, table: DataTable) -> DataTable:
        tenants = np.asarray(table[self.getTenantCol()])
        users = np.asarray(table[self.getUserCol()])
        res = np.asarray(table[self.getResCol()])
        rng = np.random.default_rng(self.getOrDefault("seed"))
        factor = self.getOrDefault("complementsetFactor")
        out_t, out_u, out_r = [], [], []
        for t in np.unique(tenants):
            m = tenants == t
            uu, ur = np.unique(users[m]), np.unique(res[m])
            seen = set(zip(users[m].tolist(), res[m].tolist()))
            total = len(uu) * len(ur)
            want = min(factor * int(m.sum()), max(total - len(seen), 0))
            got = 0
            # rejection-sample the sparse complement while acceptance is
            # likely; once the remaining complement gets small relative
            # to the ask (near-dense grid — acceptance probability
            # approaches 0 and the loop would spin unboundedly,
            # ADVICE r4), enumerate the leftover cells and draw without
            # replacement instead
            while got < want:
                remaining = total - len(seen)
                if (want - got) > 0.5 * remaining:
                    cells = [(a, b) for a in uu.tolist()
                             for b in ur.tolist() if (a, b) not in seen]
                    pick = rng.choice(len(cells), size=want - got,
                                      replace=False)
                    for j in pick:
                        a, b = cells[j]
                        seen.add((a, b))
                        out_t.append(t)
                        out_u.append(a)
                        out_r.append(b)
                    got = want
                    break
                cu = uu[rng.integers(0, len(uu), size=want - got)]
                cr = ur[rng.integers(0, len(ur), size=want - got)]
                for a, b in zip(cu.tolist(), cr.tolist()):
                    if (a, b) not in seen:
                        seen.add((a, b))
                        out_t.append(t)
                        out_u.append(a)
                        out_r.append(b)
                        got += 1
        return DataTable({
            self.getTenantCol(): np.asarray(out_t),
            self.getUserCol(): np.asarray(out_u),
            self.getResCol(): np.asarray(out_r),
        })


@partial(jax.jit, static_argnames=("n_sweeps",))
def _als_sweeps(Y, lam, U0, V0, n_sweeps: int):
    """Batched dense ALS: Y (T, m, n) binarized access matrices (padded),
    factors U (T, m, k), V (T, n, k); ridge normal equations per side."""
    k = U0.shape[-1]
    eye = jnp.eye(k, dtype=jnp.float32)

    def solve_side(Yb, F):
        # G = FᵀF + λI (T, k, k); rhs = Y F (T, m, k) → batched solve
        G = jnp.einsum("tnk,tnl->tkl", F, F) + lam * eye
        rhs = jnp.einsum("tmn,tnk->tmk", Yb, F)
        return jnp.linalg.solve(G[:, None], rhs[..., None])[..., 0]

    def sweep(carry, _):
        U, V = carry
        U = solve_side(Y, V)
        V = solve_side(jnp.swapaxes(Y, 1, 2), U)
        return (U, V), None

    (U, V), _ = jax.lax.scan(sweep, (U0, V0), None, length=n_sweeps)
    return U, V


class AccessAnomaly(_HasAccessCols, Estimator):
    """Trains the per-tenant latent-factor access model (reference
    AccessAnomaly estimator)."""

    rankParam = Param("rankParam", "Latent dimension k", default=10,
                      typeConverter=TypeConverters.toInt)
    maxIter = Param("maxIter", "ALS sweeps", default=25,
                    typeConverter=TypeConverters.toInt)
    regParam = Param("regParam", "Ridge strength lambda", default=1.0,
                     typeConverter=TypeConverters.toFloat)
    # NOTE: no complementsetFactor here, deliberately — the reference's
    # sparse Spark ALS needs SAMPLED negative pairs, but this dense
    # formulation fits every unobserved (user, res) cell as an explicit
    # zero target, so the complement set is implicit and total.
    # ComplementAccessTransformer stays available for building negative
    # sets as data (the reference's other use of it).
    outputCol = Param("outputCol", "Anomaly score output column",
                      default="anomaly_score",
                      typeConverter=TypeConverters.toString)
    seed = Param("seed", "Init/sampling seed", default=0,
                 typeConverter=TypeConverters.toInt)

    def _fit(self, table: DataTable) -> "AccessAnomalyModel":
        tenants = np.asarray(table[self.getTenantCol()])
        users = np.asarray(table[self.getUserCol()])
        res = np.asarray(table[self.getResCol()])
        k = self.getOrDefault("rankParam")

        uniq_t = list(np.unique(tenants))
        u_maps, r_maps, idx_cache = {}, {}, {}
        for t in uniq_t:
            m = tenants == t
            u_maps[t] = {v: i for i, v in enumerate(np.unique(users[m]))}
            r_maps[t] = {v: i for i, v in enumerate(np.unique(res[m]))}
        M = max(len(v) for v in u_maps.values())
        N = max(len(v) for v in r_maps.values())
        T = len(uniq_t)
        Y = np.zeros((T, M, N), np.float32)
        for ti, t in enumerate(uniq_t):
            m = tenants == t
            ui = np.asarray([u_maps[t][v] for v in users[m]])
            ri = np.asarray([r_maps[t][v] for v in res[m]])
            idx_cache[t] = (ui, ri)
            Y[ti, ui, ri] = 1.0

        # Per-tenant seeded init over the REAL slots only, zeros in the
        # padded slots.  Zero padded rows stay zero through every ridge
        # sweep (their Y rows are zero, and they contribute nothing to
        # the Gram matrices), so each tenant's fitted factors — and its
        # anomaly scores — are independent of which other tenants share
        # the batch and of the batch's padded M×N shape (ADVICE r4).
        import zlib
        seed = self.getOrDefault("seed")
        U0 = np.zeros((T, M, k), np.float32)
        V0 = np.zeros((T, N, k), np.float32)
        for ti, t in enumerate(uniq_t):
            trng = np.random.default_rng(
                [seed, zlib.crc32(str(t).encode("utf-8"))])
            mu_, nu_ = len(u_maps[t]), len(r_maps[t])
            U0[ti, :mu_] = trng.normal(scale=0.1, size=(mu_, k))
            V0[ti, :nu_] = trng.normal(scale=0.1, size=(nu_, k))
        U, V = _als_sweeps(
            jnp.asarray(Y), jnp.float32(self.getOrDefault("regParam")),
            jnp.asarray(U0), jnp.asarray(V0),
            n_sweeps=self.getOrDefault("maxIter"))
        U, V = np.asarray(U), np.asarray(V)

        # standardize per tenant over the OBSERVED pairs: scores come out
        # ~N(0,1) with high = anomalous (the reference pipes raw affinity
        # through its per-tenant StandardScalarScaler the same way)
        stats = {}
        for ti, t in enumerate(uniq_t):
            ui, ri = idx_cache[t]
            aff = np.einsum("ik,ik->i", U[ti, ui], V[ti, ri])
            sd = float(aff.std())
            stats[t] = (float(aff.mean()), sd if sd > 0 else 1.0)

        model = AccessAnomalyModel(
            tenants=uniq_t, u_maps=u_maps, r_maps=r_maps, U=U, V=V,
            stats=stats)
        return model.setParams(**{kk: vv for kk, vv in self._iterSetParams()
                                  if model.hasParam(kk)})


class AccessAnomalyModel(_HasAccessCols, Model):
    """Scores accesses: standardized NEGATIVE affinity per tenant (high =
    anomalous).  Users/resources unseen at fit time score at the
    maximally-anomalous end (affinity 0), like the reference's indexer
    mapping unseen ids outside the factor table."""

    outputCol = AccessAnomaly.outputCol

    def __init__(self, tenants=None, u_maps=None, r_maps=None, U=None,
                 V=None, stats=None, **kwargs):
        super().__init__(**kwargs)
        self._tenants = tenants or []
        self._u_maps = u_maps or {}
        self._r_maps = r_maps or {}
        self._U, self._V = U, V
        self._stats = stats or {}

    def _transform(self, table: DataTable) -> DataTable:
        tenants = np.asarray(table[self.getTenantCol()])
        users = np.asarray(table[self.getUserCol()])
        res = np.asarray(table[self.getResCol()])
        # rows of a tenant absent at fit time have NO model to be normal
        # under: score them like the most anomalous unseen pair any
        # fitted tenant can produce (affinity 0 → mu/sd), never 0.0
        # ("perfectly normal"), so an unknown tenant is not whitelisted
        unseen = max((mu / sd for mu, sd in self._stats.values()),
                     default=0.0)
        out = np.full(len(tenants), unseen, np.float64)
        for ti, t in enumerate(self._tenants):
            m = tenants == t
            if not m.any():
                continue
            um, rm = self._u_maps[t], self._r_maps[t]
            ui = np.asarray([um.get(v, -1) for v in users[m]])
            ri = np.asarray([rm.get(v, -1) for v in res[m]])
            known = (ui >= 0) & (ri >= 0)
            aff = np.zeros(int(m.sum()))
            if known.any():
                aff[known] = np.einsum(
                    "ik,ik->i", self._U[ti, ui[known]],
                    self._V[ti, ri[known]])
            mu, sd = self._stats[t]
            out[m] = (mu - aff) / sd          # high = anomalous
        return table.withColumns({self.getOrDefault("outputCol"): out})

    def _save_extra(self, path: str) -> None:
        serialize.save_arrays(path, U=self._U, V=self._V)
        t0 = self._tenants[0] if self._tenants else None
        k0 = (next(iter(self._u_maps[t0]), None)
              if t0 is not None else None)
        r0 = (next(iter(self._r_maps[t0]), None)
              if t0 is not None else None)
        serialize.save_json(path, "meta", {
            "tenants": [str(t) for t in self._tenants],
            "u_maps": {str(t): {str(k): int(v) for k, v in m.items()}
                       for t, m in self._u_maps.items()},
            "r_maps": {str(t): {str(k): int(v) for k, v in m.items()}
                       for t, m in self._r_maps.items()},
            "stats": {str(t): list(v) for t, v in self._stats.items()},
            "tenant_is_int": bool(isinstance(t0, (int, np.integer))),
            "user_is_int": bool(isinstance(k0, (int, np.integer))),
            "res_is_int": bool(isinstance(r0, (int, np.integer)))})

    def _load_extra(self, path: str) -> None:
        arrays = serialize.load_arrays(path)
        self._U, self._V = arrays["U"], arrays["V"]
        meta = serialize.load_json(path, "meta")
        tc = int if meta["tenant_is_int"] else str
        uc = int if meta["user_is_int"] else str
        rc = int if meta["res_is_int"] else str
        self._tenants = [tc(t) for t in meta["tenants"]]
        self._u_maps = {tc(t): {uc(k): v for k, v in m.items()}
                        for t, m in meta["u_maps"].items()}
        self._r_maps = {tc(t): {rc(k): v for k, v in m.items()}
                        for t, m in meta["r_maps"].items()}
        self._stats = {tc(t): tuple(v) for t, v in meta["stats"].items()}
