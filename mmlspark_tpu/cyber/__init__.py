"""Cyber-ML utilities: tenant-partitioned feature engineering + access
anomaly detection.

Re-creation of the reference's hand-written ``mmlspark/cyber`` python
package (SURVEY.md §2.1 "Hand-written Python" row; expected paths
src/main/python/mmlspark/cyber/{feature,anomaly}/*.py, UNVERIFIED):
per-tenant id indexing and scaling, complement-set sampling, and the
collaborative-filtering ``AccessAnomaly`` estimator.  The reference
implements these as PySpark window/groupBy jobs over a latent-factor
model; here the per-tenant models are padded, stacked arrays and the
ALS solves are batched dense normal equations — ``vmap``-over-tenants
matmul + Cholesky solve, the MXU shape of the same math.
"""

from .feature import (IdIndexer, IdIndexerModel, LinearScalarScaler,
                      LinearScalarScalerModel, StandardScalarScaler,
                      StandardScalarScalerModel)
from .anomaly import (AccessAnomaly, AccessAnomalyModel,
                      ComplementAccessTransformer)

__all__ = [
    "IdIndexer", "IdIndexerModel",
    "StandardScalarScaler", "StandardScalarScalerModel",
    "LinearScalarScaler", "LinearScalarScalerModel",
    "ComplementAccessTransformer",
    "AccessAnomaly", "AccessAnomalyModel",
]
