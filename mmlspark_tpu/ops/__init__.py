from .histogram import compute_histogram

__all__ = ["compute_histogram"]
