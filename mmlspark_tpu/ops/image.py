"""Batched image ops on NHWC tensors.

TPU-native replacement for the reference's OpenCV JNI image operations
(opencv/ImageTransformer.scala → OpenCV Imgproc, expected path, UNVERIFIED;
SURVEY.md §2.1-2.2).  Where the reference calls per-row JNI into OpenCV, a
TPU wants *batched* tensor ops: every op here takes/returns a float32
``(N, H, W, C)`` batch and is jit-friendly, so whole pipelines fuse into one
XLA program.  Gaussian blur is a separable depthwise convolution (MXU/VPU
work), resize is ``jax.image.resize`` (XLA gather/dot lowering).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def resize(batch: jnp.ndarray, height: int, width: int,
           method: str = "linear") -> jnp.ndarray:
    n, _, _, c = batch.shape
    return jax.image.resize(batch, (n, height, width, c), method=method)


def center_crop(batch: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    _, h, w, _ = batch.shape
    top = max((h - height) // 2, 0)
    left = max((w - width) // 2, 0)
    return batch[:, top:top + height, left:left + width, :]


def crop(batch: jnp.ndarray, top: int, left: int, height: int,
         width: int) -> jnp.ndarray:
    return batch[:, top:top + height, left:left + width, :]


def flip(batch: jnp.ndarray, horizontal: bool = True) -> jnp.ndarray:
    axis = 2 if horizontal else 1
    return jnp.flip(batch, axis=axis)


def bgr_to_rgb(batch: jnp.ndarray) -> jnp.ndarray:
    return batch[..., ::-1]


def to_grayscale(batch: jnp.ndarray, bgr: bool = True) -> jnp.ndarray:
    """ITU-R BT.601 luma; keeps a single channel."""
    if batch.shape[-1] == 1:
        return batch
    w = jnp.asarray([0.114, 0.587, 0.299] if bgr else [0.299, 0.587, 0.114],
                    batch.dtype)
    gray = jnp.tensordot(batch[..., :3], w, axes=[[-1], [0]])
    return gray[..., None]


def threshold(batch: jnp.ndarray, thresh: float, max_val: float = 255.0,
              kind: str = "binary") -> jnp.ndarray:
    if kind == "binary":
        return jnp.where(batch > thresh, max_val, 0.0)
    if kind == "binary_inv":
        return jnp.where(batch > thresh, 0.0, max_val)
    if kind == "trunc":
        return jnp.minimum(batch, thresh)
    if kind == "tozero":
        return jnp.where(batch > thresh, batch, 0.0)
    raise ValueError(f"Unknown threshold kind {kind!r}")


def _gaussian_kernel1d(size: int, sigma: float) -> jnp.ndarray:
    if sigma <= 0:  # OpenCV convention
        sigma = 0.3 * ((size - 1) * 0.5 - 1) + 0.8
    x = jnp.arange(size, dtype=jnp.float32) - (size - 1) / 2.0
    k = jnp.exp(-0.5 * (x / sigma) ** 2)
    return k / jnp.sum(k)


def gaussian_blur(batch: jnp.ndarray, size: int = 3,
                  sigma: float = 0.0) -> jnp.ndarray:
    """Separable depthwise Gaussian: two 1-D convs instead of one 2-D.

    Borders are reflected (OpenCV's BORDER_REFLECT_101 default), so the
    image mean is preserved at the edges.
    """
    k = _gaussian_kernel1d(size, sigma)
    c = batch.shape[-1]
    lo, hi = size // 2, (size - 1) // 2
    padded = jnp.pad(batch, ((0, 0), (lo, hi), (lo, hi), (0, 0)),
                     mode="reflect")
    kh = jnp.tile(k.reshape(1, size, 1, 1), (1, 1, 1, c))  # W conv
    kw = jnp.tile(k.reshape(size, 1, 1, 1), (1, 1, 1, c))  # H conv
    dn = jax.lax.conv_dimension_numbers(padded.shape, kh.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    out = jax.lax.conv_general_dilated(
        padded, kh, (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=dn, feature_group_count=c)
    out = jax.lax.conv_general_dilated(
        out, kw, (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=dn, feature_group_count=c)
    return out


def normalize(batch: jnp.ndarray, mean: Sequence[float],
              std: Sequence[float], scale: float = 1.0) -> jnp.ndarray:
    m = jnp.asarray(mean, batch.dtype)
    s = jnp.asarray(std, batch.dtype)
    return (batch * scale - m) / s


def unroll(batch: jnp.ndarray) -> jnp.ndarray:
    """HWC image batch → flat (N, H*W*C) vectors, reference UnrollImage
    layout (row-major HWC, matching the CNTK ingestion order)."""
    n = batch.shape[0]
    return batch.reshape(n, -1)
