"""Pallas TPU kernel for gradient-histogram construction.

The GBDT hot loop builds per-feature (B, 3) gradient histograms — a scatter
by bin index, the one primitive TPUs lack.  Matmul reformulations pay a
structural tax: a per-feature one-hot contraction has only ``B·3`` output
elements, so the MXU runs at ``B·3 / 128²`` ≈ 4.7 % utilization no matter
how the nibbles are split (that is what XLA's dot16 path achieves).

This kernel buys utilization back by **folding 8 features into one
128-wide matmul pair**.  With ``B = 256 = 16·16`` split into lo/hi nibbles
and combined keys

  klo = f·16 + (bin % 16)   ∈ [0, 128)
  khi = f·16 + (bin // 16)  ∈ [0, 128)

the contraction ``outᶜ = onehot(klo)ᵀ @ (onehot(khi) · ghᶜ)`` is a clean
(128, C) × (C, 128) MXU matmul per gradient channel whose **diagonal**
16×16 blocks are exactly the 8 features' histograms (off-diagonal blocks
are cross-feature garbage that costs 8× FLOPs but runs at ~100 % MXU
utilization — a net win over the 4.7 % structural bound, biggest in bf16).
Everything stays in VMEM; the kernel emits the full (3, 128, 128) product
per feature-block and XLA extracts the diagonal afterwards (in-kernel
lane slicing and reshapes are Mosaic-hostile).

``accum="bfloat16"`` runs the matmul operands in bf16 with f32
accumulation (preferred_element_type): the one-hot side is exact, only
grad/hess operand values round.

This replaces the per-feature scatter-add inside the reference's native
engine (``LGBM_BoosterUpdateOneIter`` → ConstructHistograms; SURVEY.md §3.1
hot loop).  On CPU the kernel runs in interpret mode (tests only).
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.telemetry import get_registry as _get_registry

log = logging.getLogger(__name__)

LO = 16          # low-nibble width
FB = 8           # features folded per matmul: FB * LO = 128 lanes
BMAX = LO * LO   # 256 bins supported; larger falls back to dot16


def _accum_dtypes(accum: str):
    """(matmul operand dtype, accumulator/output dtype) per accum mode.

    ``"int32"`` is the quantized-gradient mode (ISSUE 17): ``gh`` holds
    integer grid codes, both one-hot operands and the dot accumulate in
    int32, and the kernel output is EXACT int32 — order-invariant across
    chunk schedules and reduction topologies."""
    if accum == "int32":
        return jnp.int32, jnp.int32
    if accum == "bfloat16":
        return jnp.bfloat16, jnp.float32
    return jnp.float32, jnp.float32


def _hist_kernel(binsT_ref, gh_ref, out_ref, lo_scr, hi_scr, *, accum_dtype):
    """One (feature_block, row_chunk) grid step; accumulates into out_ref."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    acc_t = out_ref.dtype                 # f32, or int32 when quantized
    bT = binsT_ref[...].T                 # (C, FB) int32
    g = gh_ref[...].astype(acc_t)         # (C, 3)
    c = bT.shape[0]

    # Combined one-hots built 16 lanes at a time (per folded feature) into
    # VMEM scratch — n·(16+16) compares per row-feature instead of n·128.
    iota16 = jax.lax.broadcasted_iota(jnp.int32, (c, LO), 1)
    for f in range(FB):
        col = bT[:, f][:, None]
        lo_scr[:, f * LO:(f + 1) * LO] = (col % LO == iota16).astype(
            accum_dtype)
        hi_scr[:, f * LO:(f + 1) * LO] = (col // LO == iota16).astype(
            acc_t)

    lo_oh = lo_scr[...]
    hi_oh = hi_scr[...]
    for ch in range(3):
        rhs = (hi_oh * g[:, ch][:, None]).astype(accum_dtype)
        out_ref[0, ch] += jax.lax.dot_general(
            lo_oh, rhs, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_t)                 # (128, 128)


def _fused_kernel(binsT_ref, idx_ref, gh_ref, out_ref, lo_scr, hi_scr, *,
                  accum_dtype):
    """One (feature_block, idx_chunk) grid step of the FUSED
    gather+histogram: the full (FB, n) binsT block is VMEM-resident
    across the idx-chunk axis, so the per-segment row gather happens
    in-register instead of materializing a (size, f) sub-matrix in HBM
    (PERF.md headroom: the bucket-gather costs as much as the dot16
    histogram itself, ~26 ns/row)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    acc_t = out_ref.dtype                       # f32, or int32 (quantized)
    idx = idx_ref[...]                          # (C,) i32, pre-clamped
    g = gh_ref[...].astype(acc_t)               # (C, 3), pre-masked
    c = idx.shape[0]

    iota16 = jax.lax.broadcasted_iota(jnp.int32, (c, LO), 1)
    for f in range(FB):
        col = jnp.take(binsT_ref[f, :], idx, axis=0).astype(
            jnp.int32)[:, None]                 # VMEM gather
        lo_scr[:, f * LO:(f + 1) * LO] = (col % LO == iota16).astype(
            accum_dtype)
        hi_scr[:, f * LO:(f + 1) * LO] = (col // LO == iota16).astype(
            acc_t)

    lo_oh = lo_scr[...]
    hi_oh = hi_scr[...]
    for ch in range(3):
        rhs = (hi_oh * g[:, ch][:, None]).astype(accum_dtype)
        out_ref[0, ch] += jax.lax.dot_general(
            lo_oh, rhs, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_t)


#: VMEM budget gate for the fused kernel: the (FB, n) uint8 binsT block
#: must stay resident (plus ~1 MB of one-hot scratch and the (3,128,128)
#: accumulator), so n is capped under VMEM/FB bytes with headroom —
#: 1.5M rows = 12 MB block on a ~16 MB-VMEM core.
FUSED_MAX_ROWS = 1_500_000


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "size", "row_chunk",
                                    "accum", "interpret"))
def histogram_pallas_fused(binsT, gh_sub, idx, num_bins: int, size: int,
                           row_chunk: int = 1024, accum: str = "float32",
                           interpret: bool = False) -> jnp.ndarray:
    """Segment histogram with the row gather fused into the kernel.

    Args:
      binsT: ``(f, n)`` uint8/int32 TRANSPOSED binned matrix (the boost
        scan already keeps ``binsT`` hoisted per fit).
      gh_sub: ``(size, 3)`` float32 — the segment's gradient rows,
        gathered by the caller (12 B/row, cheap) and ZERO for padding.
      idx: ``(size,)`` int32 — the segment row ids (``row_order`` slice),
        clamped into ``[0, n)``; padded entries may repeat a valid row
        (their gh is zero).
      size: static bucket size (the grower's power-of-two ladder).

    Returns ``(f, num_bins, 3)`` float32, bit-comparable to gathering
    then calling :func:`histogram_pallas`.
    """
    if num_bins > BMAX:
        raise ValueError(f"pallas fused histogram supports ≤{BMAX} bins, "
                         f"got {num_bins}")
    f, n = binsT.shape
    if n > FUSED_MAX_ROWS:
        raise ValueError(
            f"fused kernel needs the (8, n) binsT block VMEM-resident; "
            f"n={n} exceeds {FUSED_MAX_ROWS}")
    accum_dtype, out_dtype = _accum_dtypes(accum)

    c = min(row_chunk, size)
    f_pad = (-f) % FB
    if f_pad:
        # direct callers only — the grower pre-pads binsT once per tree
        # so this whole-matrix copy never runs in the split loop
        binsT = jnp.pad(binsT, ((0, f_pad), (0, 0)))
    fp = f + f_pad
    nfb = fp // FB
    s_pad = (-size) % c
    if s_pad:
        idx = jnp.pad(idx, (0, s_pad))
        gh_sub = jnp.pad(gh_sub, ((0, s_pad), (0, 0)))

    grid = (nfb, (size + s_pad) // c)
    out = pl.pallas_call(
        functools.partial(_fused_kernel, accum_dtype=accum_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FB, n), lambda i, j: (i, 0)),   # VMEM-resident
            pl.BlockSpec((c,), lambda i, j: (j,)),
            pl.BlockSpec((c, 3), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 3, FB * LO, FB * LO),
                               lambda i, j: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nfb, 3, FB * LO, FB * LO),
                                       out_dtype),
        scratch_shapes=[
            pltpu.VMEM((c, FB * LO), accum_dtype),
            pltpu.VMEM((c, FB * LO), out_dtype),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * (size + s_pad) * nfb * 128 * 128,
            bytes_accessed=fp * n + (size + s_pad) * 16,
            transcendentals=0),
    )(binsT.astype(jnp.int32) if interpret else binsT,
      idx.astype(jnp.int32), gh_sub.astype(out_dtype))
    out = out.reshape(nfb, 3, FB, LO, FB, LO)
    diag = out[:, :, jnp.arange(FB), :, jnp.arange(FB), :]
    hist = diag.transpose(1, 0, 4, 3, 2).reshape(fp, BMAX, 3)
    return hist[:f, :num_bins, :]


#: Process-wide Mosaic-compile verdicts, keyed (backend, kernel name):
#: None/absent = not yet probed, True/False = probe outcome.  One probe
#: per (backend, method) per process — repeated fits (and the ring
#: kernels in ops/pallas_collectives.py) consult the cache instead of
#: re-compiling the probe.
_COMPILE_CACHE: dict = {}

#: Cached Mosaic-compile verdict for the fused kernel on this process's
#: backend: None = not yet probed, True/False = probe outcome.  The
#: in-kernel ``jnp.take`` row gather has only ever run in CPU interpret
#: mode (ADVICE r5); Mosaic's lowering of arbitrary dynamic gathers may
#: fail on the very hardware the kernel targets, and
#: ``histogram_method=pallas_fused`` must degrade, not hard-fail.
#: (Kept as the authoritative slot for ``pallas_fused`` — tests reset it
#: to None to force a re-probe; ``_COMPILE_CACHE`` mirrors it.)
_FUSED_COMPILE_OK: Optional[bool] = None


def probe_exposition() -> str:
    """Info-style ``/metrics`` family naming every compile-probe
    verdict this process has cached (ISSUE 12 satellite): a silent
    ``pallas_ring → pallas`` downgrade is a 0-valued sample in any
    scrape instead of one log line at fit time.  Value 1 = the kernel
    compiled on this backend, 0 = the probe failed and callers
    downgraded.  Empty until the first probe runs (no fit has resolved
    a Pallas method yet)."""
    rows = dict(_COMPILE_CACHE)
    if _FUSED_COMPILE_OK is not None:
        rows.setdefault((jax.default_backend(), "pallas_fused"),
                        _FUSED_COMPILE_OK)
    rows = {k: v for k, v in rows.items() if v is not None}
    if not rows:
        return ""
    name = "mmlspark_tpu_compile_probe_ok"
    lines = [f"# HELP {name} Compile-probe verdict per (backend, "
             "kernel method): 1 = compiles, 0 = probe failed "
             "(callers downgraded).",
             f"# TYPE {name} gauge"]
    for (backend, method), ok in sorted(rows.items()):
        lines.append(f'{name}{{backend="{backend}",'
                     f'method="{method}"}} {1 if ok else 0}')
    return "\n".join(lines) + "\n"


# join every /metrics scrape through the registry's provider hook (the
# registry skips a failing provider, never the scrape)
_get_registry().register_exposition("compile_probes", probe_exposition)


def probe_cached(method: str, probe_fn, probe: bool = True
                 ) -> Optional[bool]:
    """Run ``probe_fn`` ONCE per (backend, method) per process and cache
    whether it raised.  ``probe=False`` returns only the cached verdict
    (``None`` = unknown) without touching the device — safe under a
    trace.  Shared by the fused-histogram and ring-collective kernels."""
    key = (jax.default_backend(), method)
    if key not in _COMPILE_CACHE:
        if not probe:
            return None
        try:
            probe_fn()
            _COMPILE_CACHE[key] = True
        except Exception as e:  # noqa: BLE001 - Mosaic/XLA compile error
            log.warning(
                "pallas kernel %r failed to compile on backend %s "
                "(%s: %s); callers fall back", method, key[0],
                type(e).__name__, e)
            _COMPILE_CACHE[key] = False
    return _COMPILE_CACHE[key]


def fused_compile_supported(interpret: bool = False,
                            probe: bool = True) -> Optional[bool]:
    """Whether :func:`histogram_pallas_fused` compiles on this backend.

    With ``probe=True`` (default), compile-and-run a tiny instance ONCE
    and cache the verdict — call this from un-traced setup code (the
    engine resolves ``histogram_method`` here before building the boost
    scan).  With ``probe=False``, return only the cached verdict
    (``None`` = unknown) without touching the device — safe to consult
    from inside a trace, where launching the probe would be staged into
    the caller's jaxpr instead of executed.

    Interpret mode bypasses Mosaic entirely, so it is always supported.
    """
    global _FUSED_COMPILE_OK
    if interpret:
        return True
    if _FUSED_COMPILE_OK is None and probe:
        try:
            out = histogram_pallas_fused(
                jnp.zeros((FB, 128), jnp.uint8),
                jnp.zeros((8, 3), jnp.float32),
                jnp.zeros((8,), jnp.int32), num_bins=16, size=8)
            jax.block_until_ready(out)
            _FUSED_COMPILE_OK = True
        except Exception as e:  # noqa: BLE001 - Mosaic/XLA compile error
            log.warning(
                "pallas fused histogram failed to compile on backend "
                "%s (%s: %s); falling back to the gather-then-"
                "histogram_pallas path", jax.default_backend(),
                type(e).__name__, e)
            _FUSED_COMPILE_OK = False
    return _FUSED_COMPILE_OK


def resolve_histogram_method(method: str) -> str:
    """Downgrade a Pallas method whose kernel does not compile on this
    backend (one probe per (backend, method) per process —
    :func:`probe_cached`): ``'pallas_ring'`` → ``'pallas_fused'`` →
    ``'pallas'``.  Every other method passes through untouched.  Called
    by the GBDT engine at config-build time — i.e. OUTSIDE jit — so the
    fused branches inside the traced grower only ever consult the cached
    verdicts."""
    interpret = jax.default_backend() not in ("tpu", "axon")
    if method == "pallas_ring":
        # the ring FUSION is probed separately; when it fails, the
        # segment gather still fuses (pallas_fused) and the reduction
        # degrades to ring_allreduce_or_psum in the grower
        from .pallas_collectives import fused_ring_compile_supported
        if not fused_ring_compile_supported(interpret):
            method = "pallas_fused"
        else:
            # pallas_ring's NON-ring call sites (gate-refused buckets,
            # psum fits sharing the method string) ride the PLAIN fused
            # kernel — probe it too, so the traced gates consult a real
            # verdict instead of sailing past an unprobed None and
            # hard-failing inside jit
            fused_compile_supported(interpret)
            return method
    if method != "pallas_fused":
        return method
    if fused_compile_supported(interpret):
        return method
    return "pallas"


def histogram_pallas_fused_safe(binsT, gh_sub, idx, num_bins: int,
                                size: int, **kwargs) -> jnp.ndarray:
    """:func:`histogram_pallas_fused` with the compile-error fallback
    for direct (eager) callers: on a Mosaic/XLA failure the segment is
    gathered on-device and pushed through :func:`histogram_pallas`,
    which is bit-comparable by contract.  The verdict is cached, so
    after one failure every later call skips straight to the fallback.
    """
    global _FUSED_COMPILE_OK
    interpret = bool(kwargs.get("interpret", False))
    if fused_compile_supported(interpret) is not False:
        try:
            return histogram_pallas_fused(binsT, gh_sub, idx, num_bins,
                                          size, **kwargs)
        except Exception as e:  # noqa: BLE001 - compile failure
            log.warning(
                "pallas fused histogram call failed (%s: %s); using "
                "gather-then-histogram_pallas", type(e).__name__, e)
            _FUSED_COMPILE_OK = False
    bins_sub = jnp.take(binsT, idx, axis=1).T       # (size, f) gather
    kw = {k: v for k, v in kwargs.items()
          if k in ("row_chunk", "accum", "interpret")}
    return histogram_pallas(bins_sub, gh_sub, num_bins, **kw)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_chunk", "accum",
                                    "interpret"))
def histogram_pallas(bins: jnp.ndarray, gh: jnp.ndarray, num_bins: int,
                     row_chunk: int = 1024, accum: str = "float32",
                     interpret: bool = False) -> jnp.ndarray:
    """Per-feature gradient histograms via a VMEM-resident Pallas kernel.

    Args:
      bins: ``(n, f)`` int32 bin indices in ``[0, num_bins)``;
        num_bins ≤ 256.
      gh: ``(n, 3)`` float32 (grad, hess, count), pre-masked.
      accum: "float32" | "bfloat16" — MXU operand precision (accumulation
        is f32 via preferred_element_type) — or "int32" for the
        quantized-gradient mode: ``gh`` holds integer grid codes and the
        whole contraction runs (and returns) exact int32.

    Returns:
      ``(f, num_bins, 3)`` float32 (int32 when ``accum="int32"``).
    """
    if num_bins > BMAX:
        raise ValueError(f"pallas histogram supports ≤{BMAX} bins, "
                         f"got {num_bins}")
    n, f = bins.shape
    accum_dtype, out_dtype = _accum_dtypes(accum)

    c = min(row_chunk, max(128 * ((n + 127) // 128), 128))
    n_pad = (-n) % c
    f_pad = (-f) % FB
    # padded rows point at bin 0 with zero gh weight → no contribution
    binsT = jnp.pad(bins.T, ((0, f_pad), (0, n_pad)))
    gh = jnp.pad(gh.astype(out_dtype), ((0, n_pad), (0, 0)))
    fp, np_ = binsT.shape
    nfb = fp // FB

    grid = (nfb, np_ // c)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, accum_dtype=accum_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FB, c), lambda i, j: (i, j)),
            pl.BlockSpec((c, 3), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 3, FB * LO, FB * LO),
                               lambda i, j: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nfb, 3, FB * LO, FB * LO),
                                       out_dtype),
        scratch_shapes=[
            pltpu.VMEM((c, FB * LO), accum_dtype),
            pltpu.VMEM((c, FB * LO), out_dtype),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * np_ * nfb * 128 * 128,
            bytes_accessed=np_ * fp * 4 + np_ * 12 + nfb * 3 * 128 * 128 * 4,
            transcendentals=0),
    )(binsT.astype(jnp.int32), gh)
    # extract diagonal blocks: out[i, ch, f·16+lo, f·16+hi] → hist
    out = out.reshape(nfb, 3, FB, LO, FB, LO)
    diag = out[:, :, jnp.arange(FB), :, jnp.arange(FB), :]  # (FB, nfb, 3, LO, LO)
    # (FB, nfb, 3, lo, hi) → (nfb, FB, hi, lo, 3) → (f, B, 3)
    hist = diag.transpose(1, 0, 4, 3, 2).reshape(fp, BMAX, 3)
    return hist[:f, :num_bins, :]
