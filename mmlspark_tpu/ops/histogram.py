"""Gradient-histogram construction — the GBDT hot loop.

This is the TPU-native replacement for the per-feature histogram build inside
the reference's native engine (``LGBM_BoosterUpdateOneIter`` → ConstructHistograms;
SURVEY.md §3.1 hot loop).  The reference scatters grad/hess into per-feature
bin buffers with CPU/CUDA code; scatter-add with data-dependent indices is the
one primitive TPUs dislike, so three formulations are provided:

``segment``
    ``jax.ops.segment_sum`` per feature (vmapped).  Lowers to XLA scatter;
    correct everywhere, fastest on CPU, mediocre on TPU.

``dot16``
    Nibble-decomposed one-hot matmul.  A bin index in [0, 256) is split into
    hi/lo 4-bit halves; the histogram becomes two chained contractions
    ``loᵀ @ (hi ⊗ gh)`` that run on the MXU with 16× less transient memory
    than a naive 256-wide one-hot.  FLOPs are identical to the naive one-hot
    (n·B per channel) but the working set stays in VMEM-sized chunks.

``onehot``
    Naive one-hot einsum, row/feature chunked.  Reference implementation for
    testing the clever ones.

All accept already *masked* gradient triples ``gh = (grad, hess, count)``
(rows outside the active leaf carry zeros), which is how leaf-conditional
histograms stay static-shaped under jit — and how the same code path serves
the distributed data-parallel learner: shards build local histograms and
``psum`` them over the mesh (SURVEY.md §5.8's socket-allreduce replacement).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:                                    # jax >= 0.4.38
    from jax import ffi as _jffi
except ImportError:                     # 0.4.3x series ships jax.extend.ffi
    try:
        from jax.extend import ffi as _jffi
    except ImportError:                 # ancient jax: no FFI at all
        _jffi = None

#: channels in the gradient triple
GH_CHANNELS = 3  # grad, hess, count


_SWEEP_CACHE: dict = {}


def _sanitize_sweep(doc: dict) -> Optional[dict]:
    """Winner table with 0.0-clamped readings refused.

    A slope that clamps to 0.0 means the measurement sat below the
    dispatch-noise floor (tools/sweep_histogram.py) — the method may be
    the fastest or pure noise, so it must never be RANKED.  A winner
    entry is kept only when its own reading at that bucket is present
    and strictly positive AND no other exact method at the bucket is
    0.0-clamped (an unmeasurable rival means the ranking itself is
    unresolved).  Refused buckets fall out of the table, so
    :func:`_auto_method` falls back to the nearest larger resolved
    bucket / the backend default — exactly the committed
    ``_sweep_tpu.json`` artifacts (``pallas: 0.0`` at 2048, ``dot16:
    0.0`` at 4096/8192) demand.

    Quantized-dtype sweep rows (ISSUE 17) land in the same table under
    ``method@int16`` / ``method@int32`` keys: they are informational
    columns and must never be RANKED — a winner entry naming one is
    refused, and as rivals they are ignored (the membership check below
    only admits the four f32-exact methods)."""
    winners = doc.get("winner_by_rows") or {}
    times = doc.get("times_us_by_rows") or {}
    out = {}
    for rows, method in winners.items():
        if "@" in method:
            continue
        t = times.get(rows)
        if t is None:
            # no raw readings recorded (hand-built table): trust it
            out[rows] = method
            continue
        win_t = t.get(method)
        if win_t is None or win_t <= 0.0:
            continue
        rivals = [v for k, v in t.items()
                  if k != method and k in ("segment", "dot16", "onehot",
                                           "pallas") and v is not None]
        if any(v <= 0.0 for v in rivals):
            continue
        out[rows] = method
    return out or None


def _load_sweep(backend: str) -> Optional[dict]:
    """Measured winner-by-rows table for this backend (see
    tools/sweep_histogram.py), sanitized against 0.0-clamped noise
    artifacts, or None if never swept."""
    if backend == "axon":  # tunneled TPU: same silicon, same table
        backend = "tpu"
    if backend not in _SWEEP_CACHE:
        import json
        import os
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            f"_sweep_{backend}.json")
        table = None
        try:
            with open(path) as fh:
                table = _sanitize_sweep(json.load(fh))
        except (OSError, ValueError):
            pass
        _SWEEP_CACHE[backend] = table
    return _SWEEP_CACHE[backend]


_NATIVE_OK: Optional[bool] = None


def _native_available() -> bool:
    """Whether the XLA FFI custom calls are registered (CPU backend)."""
    global _NATIVE_OK
    if _NATIVE_OK is None:
        _NATIVE_OK = False
        try:
            from .. import native
            handlers = {
                "mmlspark_fasthist": native.hist_ffi_handler(),
                "mmlspark_fastseghist": native.seg_hist_ffi_handler(),
                "mmlspark_fastpartition": native.partition_ffi_handler(),
                "mmlspark_fastsplit": native.split_ffi_handler(),
                "mmlspark_fastqhist": native.qhist_ffi_handler(),
                "mmlspark_fastsegqhist": native.seg_qhist_ffi_handler(),
            }
            if all(h is not None for h in handlers.values()):
                for name, h in handlers.items():
                    _jffi.register_ffi_target(
                        name, _jffi.pycapsule(h), platform="cpu")
                _NATIVE_OK = True
        except Exception:  # noqa: BLE001 - no toolchain / old jax
            _NATIVE_OK = False
    return _NATIVE_OK


def _native_applies(num_bins) -> bool:
    return (num_bins <= 256 and jax.default_backend() == "cpu"
            and _native_available())


def packed_accum_ok(n_rows: int, max_code: int) -> bool:
    """Whether the packed-int64 single-add native accumulation is exact
    for ``n_rows`` quantized rows on a ``max_code`` grid: the 16-bit
    count field needs every cell's row count < 2^16 and the two biased
    24-bit g/h fields need ``n * 2*max_code < 2^24`` (each row adds at
    most ``2*max_code`` to a biased field).  Beyond the bound the C++
    kernel runs its unpacked int32x3 mode instead."""
    return (max_code > 0 and n_rows < (1 << 16)
            and n_rows * 2 * max_code < (1 << 24))


def native_segment_hist(bins, gh, row_order, off, cnt, num_bins,
                        max_code: int = 0):
    """Fused gather+histogram of the DataPartition segment
    ``row_order[off:off+cnt]`` via the FFI kernel, or None when the
    native CPU path doesn't apply (callers fall back to the bucket-ladder
    gather + :func:`compute_histogram`).  C++ loops exactly ``cnt`` rows
    — no power-of-two padding, no gathered sub-matrix materialization
    (PERF.md round-3 headroom: the bucket gather cost matched the
    histogram's)."""
    if not _native_applies(num_bins):
        return None
    f = bins.shape[1]
    if jnp.issubdtype(gh.dtype, jnp.integer):
        # quantized-gradient mode (ISSUE 17): int16 grid codes in,
        # exact int32 accumulation out; packed single-add fast mode
        # when the headroom bound holds for the WHOLE matrix (cnt is
        # dynamic, so the static gate uses n — conservative).
        packed = packed_accum_ok(bins.shape[0], max_code)
        meta = jnp.stack([off, cnt, jnp.asarray(int(packed), jnp.int32),
                          jnp.asarray(max_code, jnp.int32)]).astype(
                              jnp.int32)
        return _jffi.ffi_call(
            "mmlspark_fastsegqhist",
            jax.ShapeDtypeStruct((f, num_bins, GH_CHANNELS), jnp.int32),
        )(bins.astype(jnp.uint8), gh.astype(jnp.int16),
          row_order.astype(jnp.int32), meta)
    meta = jnp.stack([off, cnt]).astype(jnp.int32)
    return _jffi.ffi_call(
        "mmlspark_fastseghist",
        jax.ShapeDtypeStruct((f, num_bins, GH_CHANNELS), jnp.float32),
    )(bins.astype(jnp.uint8), gh.astype(jnp.float32),
      row_order.astype(jnp.int32), meta)


def native_partition(row_order, col, off, cnt, thr, use_cat, cat_bits,
                     num_bins):
    """LightGBM ``DataPartition::Split`` as one in-place stable C++ pass
    (input_output_aliases donates ``row_order``), or None when the native
    CPU path doesn't apply.  Returns ``(row_order', cnt_left,
    cnt_right)`` like the ``lax.switch`` bucket-ladder version it
    replaces — without the ladder's padding work or branch dispatch."""
    if not _native_applies(num_bins):
        return None
    m = row_order.shape[0]
    meta = jnp.stack([off, cnt, thr,
                      use_cat.astype(jnp.int32)]).astype(jnp.int32)
    ro, counts = _jffi.ffi_call(
        "mmlspark_fastpartition",
        (jax.ShapeDtypeStruct((m,), jnp.int32),
         jax.ShapeDtypeStruct((2,), jnp.int32)),
        input_output_aliases={0: 0},
    )(row_order.astype(jnp.int32), col.astype(jnp.uint8), meta,
      cat_bits.astype(jnp.uint32))
    return ro, counts[0], counts[1]


def native_find_split(hist, parent_g, parent_h, parent_c, feature_mask,
                      depth_ok, min_data_in_leaf, min_sum_hessian,
                      lambda_l1, lambda_l2, gain_floor, num_bins):
    """Numeric FindBestThreshold as one C++ pass (serial CPU path), or
    None when the native path doesn't apply.  Returns ``(gain, feat,
    bin)``; the caller supplies the is_cat/cat_bits zeros.

    The C++ scan picks the winning (feature, bin) with the same validity
    rules and first-occurrence flat order as grower.find_best_split, but
    its sequential f32 prefix sums round differently from XLA's cumsum,
    so the WINNER is what it contributes — the recorded gain is then
    recomputed here by the XLA float path on the winning feature row.
    That keeps best_gain (the best-first leaf priority) and the exported
    split_gain on XLA's float trajectory; the forests can differ from
    the pure-XLA path only when two candidates tie within prefix-sum
    rounding (fuzz-pinned winner-identical in tests/test_histogram.py)."""
    if not _native_applies(num_bins):
        return None
    parent = jnp.stack([parent_g, parent_h, parent_c]).astype(jnp.float32)
    conf = jnp.stack([
        jnp.float32(min_data_in_leaf), jnp.float32(min_sum_hessian),
        jnp.float32(lambda_l1), jnp.float32(lambda_l2),
        jnp.float32(gain_floor),
        jnp.asarray(depth_ok, jnp.float32)])
    gain_n, fb = _jffi.ffi_call(
        "mmlspark_fastsplit",
        (jax.ShapeDtypeStruct((1,), jnp.float32),
         jax.ShapeDtypeStruct((2,), jnp.int32)),
    )(hist.astype(jnp.float32), parent,
      feature_mask.astype(jnp.float32), conf)
    feat, b = fb[0], fb[1]
    l1 = jnp.float32(lambda_l1)
    l2 = jnp.float32(lambda_l2)

    def lg(g, h):
        t = jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, jnp.float32(0))
        return jnp.square(t) / (h + l2)

    row = jax.lax.dynamic_index_in_dim(hist.astype(jnp.float32), feat,
                                       axis=0, keepdims=False)   # (B, 3)
    cum = jnp.cumsum(row, axis=0)
    cell = jax.lax.dynamic_index_in_dim(cum, b, axis=0,
                                        keepdims=False)          # (3,)
    gl, hl = cell[0], cell[1]
    pg = jnp.float32(parent_g)
    ph = jnp.float32(parent_h)
    gain_x = lg(gl, hl) + lg(pg - gl, ph - hl) - lg(pg, ph)
    # The XLA-trajectory gain must ALSO clear the floor: when the C++
    # prefix-sum rounding clears it but gain_x lands at/below it, the
    # pure-XLA path would reject this split — return -inf, not a finite
    # sub-floor gain (ADVICE r4).
    gain = jnp.where(jnp.isfinite(gain_n[0])
                     & (gain_x > jnp.float32(gain_floor)),
                     gain_x, jnp.float32(-jnp.inf))
    return gain, feat, b


def _auto_method(n_rows: Optional[int] = None) -> str:
    """Pick the histogram formulation for a call site of ``n_rows`` rows.

    CPU backend: the native C++ accumulator (fasthist.cc) when the
    extension builds — it beats every XLA scatter/matmul formulation at
    all sizes on one core (~1 ns vs ~6 ns per row-feature; PERF.md).
    Otherwise this backend's measured sweep table; fall back to segment
    (CPU) / dot16 (accelerators) where no table exists."""
    backend = jax.default_backend()
    if backend == "cpu" and _native_available():
        return "native"
    table = _load_sweep(backend)
    if table and n_rows:
        for s in sorted(int(k) for k in table):
            if n_rows <= s:
                return table[str(s)]
        return table[str(max(int(k) for k in table))]
    return "dot16" if backend in ("tpu", "axon") else "segment"


def compute_histogram(bins: jnp.ndarray, gh: jnp.ndarray, num_bins: int,
                      method: str = "auto",
                      row_chunk: int = 8192,
                      max_code: int = 0) -> jnp.ndarray:
    """Per-feature gradient histograms.

    Args:
      bins: ``(n, f)`` integer bin indices in ``[0, num_bins)``.
      gh: ``(n, 3)`` float (grad, hess, count); rows not in the active leaf
        must already be zeroed.  An INTEGER dtype selects quantized mode
        (ISSUE 17): ``gh`` holds int16 grid codes and every formulation
        accumulates exactly in int32 — the result is ``(f, B, 3)`` int32
        (dequantize at split evaluation, grower-side).
      num_bins: static bin count B.
      method: "segment" | "dot16" | "onehot" | "pallas" | "pallas_bf16"
        | "auto" (plus the fused variants "pallas_fused" and
        "pallas_ring", which behave like "pallas" here — their fusion
        lives in the grower's segment path / ring collective).
      max_code: quantized mode only — the grid's |code| bound, which
        gates the native packed-int64 single-add fast path
        (:func:`packed_accum_ok`).

    Returns:
      ``(f, num_bins, 3)`` float32 histogram (int32 in quantized mode).
    """
    quantized = jnp.issubdtype(gh.dtype, jnp.integer)
    acc_dtype = jnp.int32 if quantized else jnp.float32
    if method == "auto":
        method = _auto_method(bins.shape[0])
    if method == "native":
        if num_bins > 256 or not _native_available():
            return _hist_segment(bins, gh, num_bins, acc_dtype)
        if quantized:
            return _hist_native_q(bins, gh, num_bins, max_code)
        return _hist_native(bins, gh, num_bins)
    if method == "segment":
        return _hist_segment(bins, gh, num_bins, acc_dtype)
    if method == "dot16":
        return _hist_dot16(bins, gh, num_bins, row_chunk, acc_dtype)
    if method == "onehot":
        return _hist_onehot(bins, gh, num_bins, row_chunk, acc_dtype)
    if method in ("pallas", "pallas_bf16", "pallas_fused", "pallas_ring"):
        # 'pallas_fused' fuses the SEGMENT gather (grower._segment_hist)
        # and 'pallas_ring' additionally fuses the cross-shard ring
        # reduction (ops/pallas_collectives.py); direct full-matrix
        # calls like the root histogram have nothing to gather/reduce
        # and run the plain kernel
        from .pallas_histogram import BMAX, histogram_pallas
        if num_bins > BMAX:   # kernel folds 16x16 nibbles; fall back
            return _hist_dot16(bins, gh, num_bins, row_chunk, acc_dtype)
        if quantized:
            return histogram_pallas(
                bins.astype(jnp.int32), gh.astype(jnp.int32), num_bins,
                row_chunk=min(row_chunk, 4096), accum="int32",
                interpret=jax.default_backend() == "cpu")
        return histogram_pallas(
            bins.astype(jnp.int32), gh.astype(jnp.float32), num_bins,
            row_chunk=min(row_chunk, 4096),   # VMEM ceiling for the kernel
            accum="bfloat16" if method == "pallas_bf16" else "float32",
            interpret=jax.default_backend() == "cpu")
    raise ValueError(f"Unknown histogram method {method!r}")


def _hist_native(bins, gh, num_bins):
    """CPU-backend native accumulation via an XLA FFI custom call
    (native/fasthist_ffi.cc): the C++ loop runs synchronously INSIDE the
    compiled program — no Python in the loop (a pure_callback variant
    deadlocked the single-core CPU runtime), no extra materialization, so
    this IS the fused gather+histogram path, LightGBM-style.  Never
    selected on accelerator backends (_auto_method gates on cpu)."""
    f = bins.shape[1]
    return _jffi.ffi_call(
        "mmlspark_fasthist",
        jax.ShapeDtypeStruct((f, num_bins, GH_CHANNELS), jnp.float32),
    )(bins.astype(jnp.uint8), gh.astype(jnp.float32))


def _hist_native_q(bins, gh, num_bins, max_code):
    """Quantized-gradient native accumulation (ISSUE 17): int16 grid
    codes in, exact int32 histogram out.  When :func:`packed_accum_ok`
    holds, the C++ kernel folds the (g, h, count) triple into ONE biased
    packed int64 per row and does a single 64-bit add per row-feature —
    a third of the adds and two thirds of the cell traffic of the f32
    kernel — then unpacks to (f, B, 3) int32 at the end."""
    f = bins.shape[1]
    packed = packed_accum_ok(bins.shape[0], max_code)
    meta = jnp.stack([jnp.asarray(int(packed), jnp.int32),
                      jnp.asarray(max_code, jnp.int32)]).astype(jnp.int32)
    return _jffi.ffi_call(
        "mmlspark_fastqhist",
        jax.ShapeDtypeStruct((f, num_bins, GH_CHANNELS), jnp.int32),
    )(bins.astype(jnp.uint8), gh.astype(jnp.int16), meta)


def _hist_segment(bins, gh, num_bins, acc_dtype=jnp.float32):
    gh = gh.astype(acc_dtype)

    def per_feature(col):
        return jax.ops.segment_sum(gh, col.astype(jnp.int32),
                                   num_segments=num_bins)

    # vmap over features: (f, n) -> (f, B, 3)
    return jax.vmap(per_feature)(bins.T)


def _hist_onehot(bins, gh, num_bins, row_chunk, acc_dtype=jnp.float32):
    n, f = bins.shape
    gh = gh.astype(acc_dtype)
    chunk = min(row_chunk, n)
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    bins_c = bins.reshape(-1, chunk, f)
    gh_c = gh.reshape(-1, chunk, GH_CHANNELS)

    def step(acc, args):
        b, g = args
        b = b.astype(jnp.int32)   # bins may arrive uint8; cast per chunk
        onehot = (b[:, :, None] == jnp.arange(num_bins)[None, None, :])
        acc = acc + jnp.einsum("nfb,nc->fbc", onehot.astype(acc_dtype), g)
        return acc, None

    init = jnp.zeros((f, num_bins, GH_CHANNELS), acc_dtype)
    out, _ = jax.lax.scan(step, init, (bins_c, gh_c))
    return out


def _hist_dot16(bins, gh, num_bins, row_chunk, acc_dtype=jnp.float32):
    """Nibble-decomposed histogram: B = hi*16 + lo, two MXU contractions.
    With ``acc_dtype=int32`` (quantized mode) both one-hots and the
    contraction run in integers — the MXU nibble fold accumulates the
    int one-hot matmul in int32, bit-exactly."""
    n, f = bins.shape
    n_hi = (num_bins + 15) // 16
    gh = gh.astype(acc_dtype)
    chunk = min(row_chunk, n)
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    bins_c = bins.reshape(-1, chunk, f)
    gh_c = gh.reshape(-1, chunk, GH_CHANNELS)
    lo_iota = jnp.arange(16)
    hi_iota = jnp.arange(n_hi)

    def step(acc, args):
        b, g = args                      # (c, f) int, (c, 3) f32
        b = b.astype(jnp.int32)          # bins may arrive uint8
        lo = b % 16                      # (c, f)
        hi = b // 16
        lo_oh = (lo[:, :, None] == lo_iota).astype(acc_dtype)     # (c, f, 16)
        hi_oh = (hi[:, :, None] == hi_iota).astype(acc_dtype)     # (c, f, Hh)
        # rhs[n, f, hi, ch] = hi_oh * gh  -> contract n with lo_oh
        # two-step: t = einsum('cfh,cx->cfhx') is big; fuse instead:
        # out[f, l, h, x] = sum_c lo_oh[c,f,l] * hi_oh[c,f,h] * g[c,x]
        # Do it as batched matmul per feature: (16, c) @ (c, Hh*3)
        rhs = hi_oh[:, :, :, None] * g[:, None, None, :]          # (c, f, Hh, 3)
        rhs = rhs.reshape(b.shape[0], f, n_hi * GH_CHANNELS)
        out = jnp.einsum("cfl,cfr->flr", lo_oh, rhs,
                         preferred_element_type=acc_dtype)        # (f, 16, Hh*3)
        out = out.reshape(f, 16, n_hi, GH_CHANNELS)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(
            f, n_hi * 16, GH_CHANNELS)
        return acc + out[:, :num_bins], None

    init = jnp.zeros((f, num_bins, GH_CHANNELS), acc_dtype)
    out, _ = jax.lax.scan(step, init, (bins_c, gh_c))
    return out
