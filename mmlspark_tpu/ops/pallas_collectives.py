"""On-chip fused histogram collectives — Pallas TPU ring kernels.

The distributed training hot loop reduces each split's ``(f, B, 3)``
leaf-histogram partials across the ``data`` mesh axis.  The stock path is
a bare ``jax.lax.psum`` of the whole state: XLA stages the all-reduce
through HBM and, on a tunneled chip, every dispatch pays the multi-ms RPC
floor PERF.md documents — the reason the TPU backend lost to its own CPU
fallback (BENCH_r02 ``vs_baseline`` 0.31 vs 1.39).  This module keeps the
per-tree collective entirely on-chip (ROADMAP open item 1; SNIPPETS
[1]–[3] are the exemplar ring kernels):

``ring_allreduce``
    Chunked ring reduce-scatter + all-gather of any float32 array, as one
    Pallas kernel: the array is split into one chunk per device, and at
    every step the remote DMA of the finished chunk overlaps the VPU
    accumulation of the next (double-buffered comm slots, explicit DMA
    send/recv semaphores).  At D = 2 the rotation-invariance of pairwise
    float adds makes the result BIT-IDENTICAL to ``lax.psum``; at D > 2
    each chunk's reduction visits devices in rotated ring order, so
    results differ from psum by ulp-level rounding only.

``ring_allreduce_select``
    The voted-column ring (ISSUE 16): gather ONLY the PV-Tree voted
    candidate columns — the ``(k2, B, 3)`` slab out of the full
    ``(f, B, 3)`` local histogram — and run the slab through the same
    chunked double-buffered ring schedule.  On wide data this cuts the
    collective *payload* 10–100× on top of the transport win: the
    reduce moves ``k2/f`` of the dense bytes.  The gather happens
    outside the kernel (a plain XLA take), so the ring kernel itself is
    shared with ``ring_allreduce`` — only the Mosaic collective id
    differs, keeping the two launches' barriers from aliasing when one
    program runs both.

``fused_segment_hist_ring``
    The full gather→histogram→ring-allreduce fusion: extends
    ``histogram_pallas_fused``'s VMEM-resident row gather + 16×16
    nibble-fold MXU accumulation with the ring schedule.  Feature blocks
    are grouped into one chunk per device; the kernel computes chunk
    ``my_id`` first, then at ring step ``s`` starts the remote DMA of the
    just-finished partial while the MXU accumulates the NEXT chunk's
    histogram — ICI transfer and compute overlap by construction, and the
    reduced histogram never round-trips HBM between the gather and the
    collective.

Semantics are pinned on CPU via Pallas interpret mode (remote DMAs
discharge to ``all_gather`` exchanges on a forced multi-device host
platform), which is how tier-1 tests hold without a chip; the interpret
discharge supports a single named mesh axis, so the ring path runs on a
data-only ``Mesh((D,), ("data",))`` (gbdt/distributed.py builds one when
``collective="ring"`` resolves).  Mosaic compilation on real hardware is
probe-gated per (backend, kernel) — see :func:`ring_compile_supported` —
and every caller degrades to ``lax.psum`` when the probe fails, never
hard-fails.  See docs/collectives.md for the kernel layout and knobs.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_histogram import BMAX, FB, LO, _accum_dtypes, probe_cached

log = logging.getLogger(__name__)

#: VMEM gate for the dense ring all-reduce: the flattened array plus the
#: double-buffered work/comm chunks must stay resident (the output
#: aliases the input), so arrays beyond this fall back to ``lax.psum``.
#: (f=50, B=256, 3ch) f32 is 150 KB; the gate admits every realistic
#: histogram state while refusing pathological f that would thrash VMEM.
RING_MAX_BYTES = 4 << 20

#: VMEM gate for the fused gather→hist→ring kernel: the whole (fp, n)
#: binsT block stays resident for the in-kernel gather (the DISTRIBUTED
#: shard's rows — n here is n_local = n_global / D, which is what makes
#: whole-matrix residency affordable exactly when the ring applies).
FUSED_RING_MAX_BINST_BYTES = 6 << 20

#: Mosaic collective ids for the kernel families (any constant works
#: as long as every device in the gang runs the same program; distinct
#: ids keep the kernels' barriers from aliasing).
_RING_COLLECTIVE_ID = 7
_FUSED_RING_COLLECTIVE_ID = 8
_SELECT_RING_COLLECTIVE_ID = 9


def _dev_id(i, interpret: bool):
    """Remote-DMA device id: the interpret-mode discharge wants a scalar
    logical id, Mosaic's LOGICAL lowering the 1-tuple of mesh coords."""
    return i if interpret else (i,)


# -- dense ring all-reduce ---------------------------------------------------


def _ring_allreduce_kernel(x_ref, out_ref, work, comm, send_sem, recv_sem,
                           ag_send, ag_recv, *, axis_name: str,
                           num_dev: int, interpret: bool):
    """Ring all-reduce of ``x_ref`` (D*cb, 128) into ``out_ref``.

    Reduce-scatter: D-1 steps; at step ``s`` the accumulated chunk
    ``(my_id - s) % D`` is DMA'd to the right neighbor while this device
    loads chunk ``(my_id - s - 1) % D`` — transfer of the finished chunk
    overlaps the accumulation of the next.  After the last step, device
    ``i`` holds the fully reduced chunk ``(i + 1) % D``.  All-gather:
    D-1 forwarding steps distribute the reduced chunks.  Comm slots are
    double-buffered; slot reuse is safe because step ``s``'s send data-
    depends on step ``s``'s receive (the ring is lockstep), so a slot is
    always consumed before the sender can reach its next write to it.
    """
    my_id = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my_id + 1, num_dev)
    cb = x_ref.shape[0] // num_dev

    def chunk(c):
        return pl.ds(c * cb, cb)

    # -- reduce-scatter ------------------------------------------------
    work[0] = x_ref[chunk(jax.lax.rem(my_id, num_dev))]
    for s in range(num_dev - 1):
        slot, nslot = s % 2, (s + 1) % 2
        copy = pltpu.make_async_remote_copy(
            src_ref=work.at[slot], dst_ref=comm.at[nslot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[nslot],
            device_id=_dev_id(right, interpret),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        copy.start()
        # overlap: load the next chunk's local contribution while the
        # finished chunk is on the wire
        c_next = jax.lax.rem(my_id - (s + 1) + num_dev, num_dev)
        work[nslot] = x_ref[chunk(c_next)]
        copy.wait()
        work[nslot] += comm[nslot]

    own = jax.lax.rem(my_id + 1, num_dev)
    red_slot = (num_dev - 1) % 2
    out_ref[chunk(own)] = work[red_slot]

    # -- all-gather ----------------------------------------------------
    comm[red_slot] = work[red_slot]
    for s in range(num_dev - 1):
        slot = (s + num_dev - 1) % 2
        nslot = (s + num_dev) % 2
        copy = pltpu.make_async_remote_copy(
            src_ref=comm.at[slot], dst_ref=comm.at[nslot],
            send_sem=ag_send.at[slot], recv_sem=ag_recv.at[nslot],
            device_id=_dev_id(right, interpret),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        copy.start()
        copy.wait()
        c = jax.lax.rem(my_id - s + num_dev, num_dev)
        out_ref[chunk(c)] = comm[nslot]


def _ring_flat(x: jnp.ndarray, axis_name: str, num_devices: int,
               interpret: bool, collective_id: int) -> jnp.ndarray:
    """Shared launcher for the dense/select ring: flatten, pad to one
    (cb, 128) chunk per device, run :func:`_ring_allreduce_kernel` under
    the given Mosaic collective id, unpad."""
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    total = flat.shape[0]
    rows = -(-total // 128)
    cb = -(-rows // num_devices)
    pad = num_devices * cb * 128 - total
    if pad:
        flat = jnp.pad(flat, (0, pad))
    arr = flat.reshape(num_devices * cb, 128)
    out = pl.pallas_call(
        functools.partial(_ring_allreduce_kernel, axis_name=axis_name,
                          num_dev=num_devices, interpret=interpret),
        out_shape=jax.ShapeDtypeStruct(arr.shape, jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, cb, 128), jnp.float32),
            pltpu.VMEM((2, cb, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        input_output_aliases={0: 0},
        interpret=interpret,
        **({} if interpret else dict(
            compiler_params=pltpu.TPUCompilerParams(
                collective_id=collective_id))),
    )(arr)
    return out.reshape(-1)[:total].reshape(shape).astype(dtype)


def ring_allreduce(x: jnp.ndarray, axis_name: str, num_devices: int,
                   interpret: bool = False) -> jnp.ndarray:
    """Pallas ring all-reduce of ``x`` over ``axis_name`` (call inside
    ``shard_map`` on a SINGLE-named-axis mesh).  Drop-in for
    ``jax.lax.psum(x, axis_name)``; bit-identical at ``num_devices=2``,
    ulp-rotated at larger rings.  Raises when the VMEM gate refuses the
    array — trace-safe callers use :func:`ring_allreduce_or_psum`."""
    if num_devices <= 1:
        return x
    if 4 * int(np.prod(x.shape)) > RING_MAX_BYTES:
        raise ValueError(
            f"ring_allreduce: {x.shape} f32 exceeds the "
            f"{RING_MAX_BYTES >> 20} MB VMEM-residency gate")
    return _ring_flat(x, axis_name, num_devices, interpret,
                      _RING_COLLECTIVE_ID)


def ring_allreduce_or_psum(x: jnp.ndarray, axis_name: str,
                           num_devices: int) -> jnp.ndarray:
    """Trace-safe psum replacement: the ring kernel when the cached
    compile verdict and the VMEM gate allow it, ``lax.psum`` otherwise.
    Consults only CACHED probe verdicts (``probe=False``) so it is safe
    to call from inside a jitted/shard_mapped trace — the engine probes
    at config-build time via :func:`resolve_collective`."""
    interpret = jax.default_backend() not in ("tpu", "axon")
    if (num_devices > 1
            and 4 * int(np.prod(x.shape)) <= RING_MAX_BYTES
            and ring_compile_supported(interpret, probe=False)
            is not False):
        return ring_allreduce(x, axis_name, num_devices,
                              interpret=interpret)
    return jax.lax.psum(x, axis_name)


# -- voted-column ring: gather the candidate slab, ring only the slab --------


def _gather_cand(hist: jnp.ndarray, cand: jnp.ndarray) -> jnp.ndarray:
    """Gather the voted candidate columns: ``(f, B, 3)[cand (k2,)]`` →
    ``(k2, B, 3)``, or the batched-frontier layout ``(m, f, B, 3)`` with
    ``cand (m, k2)`` → ``(m, k2, B, 3)`` (m children share one launch)."""
    if cand.ndim == 1:
        return jnp.take(hist, cand, axis=0)
    return jnp.take_along_axis(hist, cand[:, :, None, None], axis=1)


def ring_allreduce_select(hist: jnp.ndarray, cand: jnp.ndarray,
                          axis_name: str, num_devices: int,
                          interpret: bool = False) -> jnp.ndarray:
    """Voted-column ring all-reduce (PV-Tree candidate reduction).

    Gathers ``hist[cand]`` — the ``(k2, B, 3)`` voted-candidate slab of
    a shard-LOCAL ``(f, B, 3)`` histogram, or the stacked ``(m, k2, B,
    3)`` slab of a batched frontier — and runs ONLY the slab through the
    chunked double-buffered ring schedule.  Same numerics contract as
    :func:`ring_allreduce` (bit-identical to gather+psum at D=2,
    ulp-rotated beyond), under its own Mosaic collective id so the dense
    and voted rings never share a barrier.  Raises when the VMEM gate
    refuses the slab — trace-safe callers use
    :func:`ring_allreduce_select_or_psum`."""
    slab = _gather_cand(hist, cand)
    if num_devices <= 1:
        return slab
    if 4 * int(np.prod(slab.shape)) > RING_MAX_BYTES:
        raise ValueError(
            f"ring_allreduce_select: slab {slab.shape} f32 exceeds the "
            f"{RING_MAX_BYTES >> 20} MB VMEM-residency gate")
    return _ring_flat(slab, axis_name, num_devices, interpret,
                      _SELECT_RING_COLLECTIVE_ID)


def ring_allreduce_select_or_psum(hist: jnp.ndarray, cand: jnp.ndarray,
                                  axis_name: str,
                                  num_devices: int) -> jnp.ndarray:
    """Trace-safe voted-column reduction: the select-ring when the
    cached compile verdict and the VMEM gate allow it, gather +
    ``lax.psum`` otherwise.  The Mosaic verdict is the dense ring's
    (``ring_compile_supported``): the kernel is byte-for-byte the same
    program, only the collective id differs, so one probe covers both."""
    slab = _gather_cand(hist, cand)
    interpret = jax.default_backend() not in ("tpu", "axon")
    if (num_devices > 1
            and 4 * int(np.prod(slab.shape)) <= RING_MAX_BYTES
            and ring_compile_supported(interpret, probe=False)
            is not False):
        return _ring_flat(slab, axis_name, num_devices, interpret,
                          _SELECT_RING_COLLECTIVE_ID)
    return jax.lax.psum(slab, axis_name)


# -- fused gather → segment histogram → ring all-reduce ----------------------


def _fused_hist_ring_kernel(binsT_ref, idx_ref, gh_ref, out_ref,
                            work, comm, lo_scr, hi_scr,
                            send_sem, recv_sem, ag_send, ag_recv, *,
                            axis_name: str, num_dev: int, cb: int,
                            row_chunk: int, n_row_chunks: int,
                            accum_dtype, interpret: bool):
    """Gather + nibble-fold histogram + ring reduce in ONE kernel.

    Feature blocks are grouped into ``num_dev`` chunks of ``cb`` blocks.
    The reduce-scatter loop computes chunk ``(my_id - s) % D``'s local
    histogram with the MXU (in-VMEM row gather, exactly the
    ``histogram_pallas_fused`` inner loop) WHILE the previous chunk's
    partial rides the ICI to the right neighbor — the overlap the
    per-tree collective was paying HBM+RPC round-trips for.  The
    accumulation order inside each (block, channel) product is identical
    to ``histogram_pallas_fused`` (ascending row chunks), so at D = 2
    the result is bit-identical to gather→hist→psum.
    """
    my_id = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my_id + 1, num_dev)
    acc_t = out_ref.dtype          # f32, or int32 when quantized
    c = row_chunk
    iota16 = jax.lax.broadcasted_iota(jnp.int32, (c, LO), 1)

    def compute_chunk(chunk_idx, slot):
        """Local histogram of feature-block chunk ``chunk_idx`` into
        ``work[slot]`` — the _fused_kernel gather+MXU loop, with the
        block row offset dynamic (it depends on ``my_id``)."""
        for b in range(cb):
            row0 = (chunk_idx * cb + b) * FB
            for ch in range(3):
                work[slot, b, ch] = jnp.zeros_like(work[slot, b, ch])

            def row_body(j, _):
                idxc = idx_ref[pl.ds(j * c, c)]
                g = gh_ref[pl.ds(j * c, c), :].astype(acc_t)
                for f in range(FB):
                    col = jnp.take(
                        binsT_ref[pl.ds(row0 + f, 1), :][0], idxc,
                        axis=0).astype(jnp.int32)[:, None]
                    lo_scr[:, f * LO:(f + 1) * LO] = \
                        (col % LO == iota16).astype(accum_dtype)
                    hi_scr[:, f * LO:(f + 1) * LO] = \
                        (col // LO == iota16).astype(acc_t)
                lo_oh = lo_scr[...]
                hi_oh = hi_scr[...]
                for ch in range(3):
                    rhs = (hi_oh * g[:, ch][:, None]).astype(accum_dtype)
                    work[slot, b, ch] += jax.lax.dot_general(
                        lo_oh, rhs,
                        dimension_numbers=(((0,), (0,)), ((), ())),
                        preferred_element_type=acc_t)
                return 0

            jax.lax.fori_loop(0, n_row_chunks, row_body, 0)

    def chunk(cix):
        return pl.ds(cix * cb, cb)

    # -- fused reduce-scatter: compute overlaps the in-flight transfer --
    compute_chunk(jax.lax.rem(my_id, num_dev), 0)
    for s in range(num_dev - 1):
        slot, nslot = s % 2, (s + 1) % 2
        copy = pltpu.make_async_remote_copy(
            src_ref=work.at[slot], dst_ref=comm.at[nslot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[nslot],
            device_id=_dev_id(right, interpret),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        copy.start()
        # MXU accumulation of the NEXT chunk while the finished chunk's
        # partial is on the wire
        compute_chunk(jax.lax.rem(my_id - (s + 1) + num_dev, num_dev),
                      nslot)
        copy.wait()
        for b in range(cb):
            for ch in range(3):
                work[nslot, b, ch] += comm[nslot, b, ch]

    own = jax.lax.rem(my_id + 1, num_dev)
    red_slot = (num_dev - 1) % 2
    out_ref[chunk(own)] = work[red_slot]

    # -- all-gather of the reduced chunks ------------------------------
    comm[red_slot] = work[red_slot]
    for s in range(num_dev - 1):
        slot = (s + num_dev - 1) % 2
        nslot = (s + num_dev) % 2
        copy = pltpu.make_async_remote_copy(
            src_ref=comm.at[slot], dst_ref=comm.at[nslot],
            send_sem=ag_send.at[slot], recv_sem=ag_recv.at[nslot],
            device_id=_dev_id(right, interpret),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        copy.start()
        copy.wait()
        cix = jax.lax.rem(my_id - s + num_dev, num_dev)
        out_ref[chunk(cix)] = comm[nslot]


def fused_ring_applicable(f: int, n: int, num_bins: int,
                          num_devices: int) -> bool:
    """Static gate for the fused gather→hist→ring kernel: bins must fit
    the nibble fold, the shard's binsT block must fit VMEM, and the comm
    buffers (2×2 chunks of cb (3,128,128) products) must stay modest."""
    if num_devices <= 1 or num_bins > BMAX:
        return False
    fp = f + ((-f) % (FB * num_devices))
    if fp * n > FUSED_RING_MAX_BINST_BYTES:
        return False
    cb = fp // FB // num_devices
    # out + work + comm VMEM budget: (D*cb + 4*cb) products of 196 KB
    return (num_devices * cb + 4 * cb) * 3 * 128 * 128 * 4 <= (8 << 20)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "size", "axis_name",
                                    "num_devices", "row_chunk", "accum",
                                    "interpret"))
def fused_segment_hist_ring(binsT, gh_sub, idx, num_bins: int, size: int,
                            axis_name: str, num_devices: int,
                            row_chunk: int = 1024, accum: str = "float32",
                            interpret: bool = False) -> jnp.ndarray:
    """Segment histogram with the row gather AND the cross-shard
    reduction fused into one kernel (call inside ``shard_map``).

    Args mirror :func:`mmlspark_tpu.ops.pallas_histogram.
    histogram_pallas_fused` — ``binsT`` is THIS SHARD's (f, n_local)
    transposed binned matrix, ``idx``/``gh_sub`` the shard's segment rows
    (pre-clamped/pre-masked, padded entries zero-weighted) — plus the
    mesh axis to reduce over.  Every shard must call with the same
    static ``size`` (the grower picks the bucket from the global max
    count when the ring is active).  Returns the REDUCED (f, num_bins,
    3) histogram, bit-comparable at D=2 to gathering, calling
    ``histogram_pallas_fused`` and ``psum``-ing the partials.
    """
    if num_bins > BMAX:
        raise ValueError(f"fused ring histogram supports ≤{BMAX} bins, "
                         f"got {num_bins}")
    f, n = binsT.shape
    if not fused_ring_applicable(f, n, num_bins, num_devices):
        raise ValueError(
            f"fused ring histogram gate refused (f={f}, n={n}, "
            f"D={num_devices}); callers fall back to "
            f"histogram_pallas_fused + ring_allreduce_or_psum")
    accum_dtype, out_dtype = _accum_dtypes(accum)

    c = min(row_chunk, size)
    # pad feature blocks to one chunk of cb blocks per device
    f_pad = (-f) % (FB * num_devices)
    if f_pad:
        binsT = jnp.pad(binsT, ((0, f_pad), (0, 0)))
    fp = f + f_pad
    nfb = fp // FB
    cb = nfb // num_devices
    s_pad = (-size) % c
    if s_pad:
        idx = jnp.pad(idx, (0, s_pad))
        gh_sub = jnp.pad(gh_sub, ((0, s_pad), (0, 0)))

    out = pl.pallas_call(
        functools.partial(
            _fused_hist_ring_kernel, axis_name=axis_name,
            num_dev=num_devices, cb=cb, row_chunk=c,
            n_row_chunks=(size + s_pad) // c, accum_dtype=accum_dtype,
            interpret=interpret),
        out_shape=jax.ShapeDtypeStruct((nfb, 3, FB * LO, FB * LO),
                                       out_dtype),
        scratch_shapes=[
            pltpu.VMEM((2, cb, 3, FB * LO, FB * LO), out_dtype),
            pltpu.VMEM((2, cb, 3, FB * LO, FB * LO), out_dtype),
            pltpu.VMEM((c, FB * LO), accum_dtype),
            pltpu.VMEM((c, FB * LO), out_dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        **({} if interpret else dict(
            compiler_params=pltpu.TPUCompilerParams(
                collective_id=_FUSED_RING_COLLECTIVE_ID))),
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * (size + s_pad) * nfb * 128 * 128,
            bytes_accessed=fp * n + (size + s_pad) * 16,
            transcendentals=0),
        interpret=interpret,
    )(binsT.astype(jnp.int32) if interpret else binsT,
      idx.astype(jnp.int32), gh_sub.astype(out_dtype))
    # extract the diagonal 16x16 blocks, exactly like histogram_pallas
    out = out.reshape(nfb, 3, FB, LO, FB, LO)
    diag = out[:, :, jnp.arange(FB), :, jnp.arange(FB), :]
    hist = diag.transpose(1, 0, 4, 3, 2).reshape(fp, BMAX, 3)
    return hist[:f, :num_bins, :]


# -- compile probes / resolution ---------------------------------------------


def _data_only_probe_mesh():
    from jax.sharding import Mesh
    from ..core.mesh import DATA_AXIS
    devs = np.asarray(jax.devices())
    return Mesh(devs, (DATA_AXIS,)), DATA_AXIS, len(devs)


def _shard_map(f, mesh, in_specs, out_specs):
    from ..core.mesh import shard_map_compat
    return shard_map_compat(f, mesh, in_specs, out_specs)


def _probe_ring_once():
    from jax.sharding import PartitionSpec as P
    mesh, ax, d = _data_only_probe_mesh()
    x = jnp.zeros((d * 2, 128), jnp.float32)
    fn = jax.jit(_shard_map(
        lambda a: ring_allreduce(a, ax, d, interpret=False),
        mesh, P(ax, None), P(ax, None)))
    jax.block_until_ready(fn(x))


def _probe_fused_ring_once():
    from jax.sharding import PartitionSpec as P
    mesh, ax, d = _data_only_probe_mesh()
    f, n, size = FB * d, 256, 64
    binsT = jnp.zeros((d * f, n), jnp.uint8)
    gh = jnp.zeros((d * size, 3), jnp.float32)
    idx = jnp.zeros((d * size,), jnp.int32)
    fn = jax.jit(_shard_map(
        lambda b, g, i: fused_segment_hist_ring(
            b, g, i, 16, size, ax, d, interpret=False),
        mesh, (P(ax, None), P(ax, None), P(ax)), P(ax, None, None)))
    jax.block_until_ready(fn(binsT, gh, idx))


def ring_compile_supported(interpret: bool = False,
                           probe: bool = True) -> Optional[bool]:
    """Whether the ring all-reduce kernel compiles and runs on this
    backend's full device set.  Cached process-wide per (backend,
    kernel); ``probe=False`` returns only the cached verdict (trace-
    safe).  Interpret mode bypasses Mosaic and is always supported."""
    if interpret:
        return True
    if len(jax.devices()) <= 1:
        return False       # nothing to ring over
    return probe_cached("ring_allreduce", _probe_ring_once, probe=probe)


def fused_ring_compile_supported(interpret: bool = False,
                                 probe: bool = True) -> Optional[bool]:
    """Mosaic verdict for the fused gather→hist→ring kernel (same
    contract as :func:`ring_compile_supported`)."""
    if interpret:
        return True
    if len(jax.devices()) <= 1:
        return False
    return probe_cached("fused_segment_hist_ring", _probe_fused_ring_once,
                        probe=probe)


def resolve_collective(collective: str, data_shards: int = 0) -> str:
    """Resolve the training ``collective`` knob to "psum" or "ring".

    "auto" stays on psum (the ring is opt-in until an on-chip A/B lands
    — tools/tpu_session.sh queues one); "ring" downgrades to psum with a
    warning when the kernel does not compile on this backend or there is
    only one data shard.  Called OUTSIDE jit at config-build time, so
    traced code only ever consults the cached verdicts."""
    if collective in ("auto", "psum", ""):
        return "psum"
    if collective != "ring":
        raise ValueError(f"Unknown collective {collective!r}; "
                         "valid: auto, psum, ring")
    if data_shards <= 1:
        return "psum"
    interpret = jax.default_backend() not in ("tpu", "axon")
    if ring_compile_supported(interpret):
        return "ring"
    log.warning("collective='ring' requested but the Pallas ring kernel "
                "does not compile on backend %s; falling back to psum",
                jax.default_backend())
    return "psum"
