"""MurmurHash3 (x86 32-bit) feature hashing.

Spark's ``HashingTF`` and VW both hash terms with murmur3-32; the reference
inherits that through Spark ML and the VW JNI featurizer
(featurize/text/TextFeaturizer.scala and vw/VowpalWabbitFeaturizer.scala,
expected paths, UNVERIFIED — SURVEY.md §2.1).  This implementation matches
Spark's ``Murmur3_x86_32`` on UTF-8 bytes with the default seed 42, so hashed
feature indices are bit-compatible with the reference's — a model trained
there scores identically here.

A C++ fast path (``mmlspark_tpu.native``) is used automatically when the
native library is built; this pure-python fallback keeps CI hermetic.
"""

from __future__ import annotations

from typing import Iterable, List

_MASK = 0xFFFFFFFF


def murmur3_32(data: bytes, seed: int = 42) -> int:
    """murmur3 x86 32-bit of ``data``; returns a *signed* int32 like the JVM."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & _MASK
    n4 = len(data) // 4 * 4
    for i in range(0, n4, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & _MASK
        k = ((k << 15) | (k >> 17)) & _MASK
        k = (k * c2) & _MASK
        h ^= k
        h = ((h << 13) | (h >> 19)) & _MASK
        h = (h * 5 + 0xE6546B64) & _MASK
    tail = data[n4:]
    if tail:
        k = int.from_bytes(tail.ljust(4, b"\0"), "little")
        k = (k * c1) & _MASK
        k = ((k << 15) | (k >> 17)) & _MASK
        k = (k * c2) & _MASK
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h - (1 << 32) if h >= (1 << 31) else h


def _native_hasher():
    try:
        from mmlspark_tpu import native
        if native.available():
            return native.murmur3_batch
    except ImportError:  # pragma: no cover
        pass
    return None


def hash_term(term: str, num_features: int, seed: int = 42) -> int:
    """Non-negative bucket index of ``term`` (Spark HashingTF semantics)."""
    return murmur3_32(term.encode("utf-8"), seed) % num_features


def hash_terms(terms: Iterable[str], num_features: int,
               seed: int = 42) -> List[int]:
    native = _native_hasher()
    if native is not None:
        return [h % num_features for h in native(list(terms), seed)]
    return [hash_term(t, num_features, seed) for t in terms]
