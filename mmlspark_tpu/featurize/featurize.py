"""Tabular auto-featurization stages.

Reference: featurize/Featurize.scala, featurize/CleanMissingData.scala,
featurize/ValueIndexer.scala, featurize/DataConversion.scala,
featurize/CountSelector.scala (expected paths, UNVERIFIED — SURVEY.md §2.1).

The TPU-first reading of this package: its job is to turn arbitrary host
tables into the dense, statically-shaped float matrices the accelerator
wants.  All the logic here is host-side numpy (it runs once per fit over
host data); its *output* — a fixed-width ``features`` vector column — is
what flows to the jit'd learners.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.params import (
    HasInputCol, HasInputCols, HasOutputCol, Param, TypeConverters)
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import DataTable
from ..core import serialize


def _is_numeric(col: np.ndarray) -> bool:
    return col.dtype.kind in "fiub"


# ---------------------------------------------------------------------------
# DataConversion
# ---------------------------------------------------------------------------

_CONVERSIONS = {
    "boolean": np.bool_, "byte": np.int8, "short": np.int16,
    "integer": np.int32, "long": np.int64, "float": np.float32,
    "double": np.float64, "string": None,
}


class DataConversion(Transformer):
    """Casts columns to a target type (reference featurize/DataConversion)."""

    cols = Param("cols", "Comma-separated list of columns to convert",
                 typeConverter=TypeConverters.toListString)
    convertTo = Param("convertTo", "The result type", default="",
                      typeConverter=TypeConverters.toString,
                      validator=lambda v: v in _CONVERSIONS or v == "")
    dateTimeFormat = Param("dateTimeFormat",
                           "Format for DateTime when making DateTime:String conversions",
                           default="yyyy-MM-dd HH:mm:ss",
                           typeConverter=TypeConverters.toString)

    def _transform(self, table: DataTable) -> DataTable:
        target = self.getConvertTo()
        out = {}
        for name in self.getCols():
            col = table[name]
            if target == "string":
                out[name] = col.astype(str).astype(object)
            else:
                out[name] = col.astype(_CONVERSIONS[target])
        return table.withColumns(out)


# ---------------------------------------------------------------------------
# CleanMissingData
# ---------------------------------------------------------------------------

class _CleanMissingParams(HasInputCols):
    outputCols = Param("outputCols", "Output column names",
                       default=None, typeConverter=TypeConverters.toListString)
    cleaningMode = Param("cleaningMode", "Cleaning mode: Mean, Median or Custom",
                         default="Mean", typeConverter=TypeConverters.toString,
                         validator=lambda v: v in ("Mean", "Median", "Custom"))
    customValue = Param("customValue", "Custom value for replacement "
                        "(Custom mode)", default=None)


class CleanMissingData(_CleanMissingParams, Estimator):
    """Fills NaN/missing values with mean/median/custom fill values computed
    at fit time (reference featurize/CleanMissingData.scala)."""

    def _fit(self, table: DataTable) -> "CleanMissingDataModel":
        mode = self.getCleaningMode()
        fills: List[float] = []
        for name in self.getInputCols():
            col = np.asarray(table[name], dtype=np.float64)
            if mode == "Custom":
                fill = float(self.getCustomValue())
            else:
                with np.errstate(all="ignore"):
                    import warnings
                    with warnings.catch_warnings():
                        warnings.simplefilter("ignore", RuntimeWarning)
                        fill = float(np.nanmean(col) if mode == "Mean"
                                     else np.nanmedian(col))
                if not np.isfinite(fill):  # all-NaN column
                    fill = 0.0
            fills.append(fill)
        model = CleanMissingDataModel(fills=fills)
        model.setParams(**{k: v for k, v in self._iterSetParams()})
        return model


class CleanMissingDataModel(_CleanMissingParams, Model):
    def __init__(self, fills: Optional[List[float]] = None, **kwargs):
        super().__init__(**kwargs)
        self._fills = list(fills or [])

    @property
    def fillValues(self) -> List[float]:
        return list(self._fills)

    def _transform(self, table: DataTable) -> DataTable:
        in_cols = self.getInputCols()
        out_cols = self.getOutputCols() or in_cols
        updates = {}
        for name, out, fill in zip(in_cols, out_cols, self._fills):
            col = np.asarray(table[name], dtype=np.float64)
            updates[out] = np.where(np.isnan(col), fill, col)
        return table.withColumns(updates)

    def _save_extra(self, path: str) -> None:
        serialize.save_json(path, "fills", self._fills)

    def _load_extra(self, path: str) -> None:
        self._fills = [float(x) for x in serialize.load_json(path, "fills")]


# ---------------------------------------------------------------------------
# ValueIndexer / IndexToValue
# ---------------------------------------------------------------------------

class ValueIndexer(HasInputCol, HasOutputCol, Estimator):
    """Indexes a column's distinct values into [0, numLevels) by sorted order
    (reference featurize/ValueIndexer.scala)."""

    def _fit(self, table: DataTable) -> "ValueIndexerModel":
        col = table[self.getInputCol()]
        levels = sorted({_scalar(v) for v in col if not _is_missing(v)},
                        key=lambda x: (str(type(x)), x))
        model = ValueIndexerModel(levels=levels)
        model.setParams(**{k: v for k, v in self._iterSetParams()})
        return model


class ValueIndexerModel(HasInputCol, HasOutputCol, Model):
    def __init__(self, levels: Optional[List[Any]] = None, **kwargs):
        super().__init__(**kwargs)
        self._levels = list(levels or [])

    @property
    def levels(self) -> List[Any]:
        return list(self._levels)

    def _transform(self, table: DataTable) -> DataTable:
        index = {v: i for i, v in enumerate(self._levels)}
        col = table[self.getInputCol()]
        out = np.asarray([index.get(_scalar(v), -1) for v in col],
                         dtype=np.int64)
        return table.withColumn(self.getOutputCol(), out)

    def _save_extra(self, path: str) -> None:
        serialize.save_json(path, "levels", self._levels)

    def _load_extra(self, path: str) -> None:
        self._levels = serialize.load_json(path, "levels")


class IndexToValue(HasInputCol, HasOutputCol, Transformer):
    """Inverse of :class:`ValueIndexerModel` given its levels
    (reference featurize/IndexToValue.scala)."""

    levels = Param("levels", "Ordered distinct values; index i maps to levels[i]",
                   typeConverter=TypeConverters.toList)

    def _transform(self, table: DataTable) -> DataTable:
        levels = self.getLevels()
        idx = np.asarray(table[self.getInputCol()], dtype=np.int64)
        out = np.empty(len(idx), dtype=object)
        for i, v in enumerate(idx):
            out[i] = levels[v] if 0 <= v < len(levels) else None
        return table.withColumn(self.getOutputCol(), out)


def _scalar(v: Any) -> Any:
    if isinstance(v, (np.generic,)):
        return v.item()
    return v


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    if isinstance(v, np.floating) and np.isnan(v):
        return True
    return False


# ---------------------------------------------------------------------------
# CountSelector
# ---------------------------------------------------------------------------

class CountSelector(HasInputCol, HasOutputCol, Estimator):
    """Drops vector slots that are all-zero in the fitting data
    (reference featurize/CountSelector.scala)."""

    def _fit(self, table: DataTable) -> "CountSelectorModel":
        mat = np.asarray(table[self.getInputCol()], dtype=np.float64)
        keep = np.flatnonzero(np.any(mat != 0, axis=0)).astype(np.int64)
        model = CountSelectorModel(indices=keep)
        model.setParams(**{k: v for k, v in self._iterSetParams()})
        return model


class CountSelectorModel(HasInputCol, HasOutputCol, Model):
    def __init__(self, indices: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self._indices = np.asarray(
            indices if indices is not None else [], dtype=np.int64)

    @property
    def indices(self) -> np.ndarray:
        return self._indices.copy()

    def _transform(self, table: DataTable) -> DataTable:
        mat = np.asarray(table[self.getInputCol()], dtype=np.float64)
        return table.withColumn(self.getOutputCol(), mat[:, self._indices])

    def _save_extra(self, path: str) -> None:
        serialize.save_arrays(path, indices=self._indices)

    def _load_extra(self, path: str) -> None:
        self._indices = serialize.load_arrays(path)["indices"]


# ---------------------------------------------------------------------------
# Featurize / AssembleFeatures
# ---------------------------------------------------------------------------

class _FeaturizeParams(HasOutputCol):
    inputCols = Param("inputCols", "The columns to featurize",
                      typeConverter=TypeConverters.toListString)
    outputCol = Param("outputCol", "The output (assembled features) column",
                      default="features", typeConverter=TypeConverters.toString)
    oneHotEncodeCategoricals = Param(
        "oneHotEncodeCategoricals", "One-hot encode categorical columns",
        default=True, typeConverter=TypeConverters.toBool)
    numFeatures = Param(
        "numFeatures",
        "Hash dimension for high-cardinality string columns (0 = index, "
        "never hash)", default=262144, typeConverter=TypeConverters.toInt)
    imputeMissing = Param("imputeMissing",
                          "Mean-impute NaNs in numeric columns",
                          default=True, typeConverter=TypeConverters.toBool)


_MAX_ONE_HOT = 64  # cardinality cutoff between one-hot and hashing


class Featurize(_FeaturizeParams, Estimator):
    """Auto-vectorizes mixed-type columns into one dense ``features`` vector
    (reference featurize/Featurize.scala + AssembleFeatures.scala).

    Per-column plan chosen at fit time:

    * numeric scalar → mean-imputed float slot
    * numeric vector → passthrough slots
    * low-cardinality string/object → one-hot (or index when
      ``oneHotEncodeCategoricals=False``)
    * high-cardinality string/object → murmur3 hashing into
      ``numFeatures`` slots is *not* materialized densely; instead the
      value hashes into ``min(numFeatures, 4096)`` slots to keep the
      assembled vector dense and TPU-friendly
    """

    def _fit(self, table: DataTable) -> "FeaturizeModel":
        specs: List[Dict[str, Any]] = []
        for name in self.getInputCols():
            col = table[name]
            if col.ndim >= 2:
                specs.append({"col": name, "kind": "vector",
                              "width": int(np.prod(col.shape[1:]))})
            elif _is_numeric(col):
                colf = col.astype(np.float64)
                mean = float(np.nanmean(colf)) if len(colf) else 0.0
                if not np.isfinite(mean):
                    mean = 0.0
                specs.append({"col": name, "kind": "numeric", "mean": mean})
            else:
                values = [str(_scalar(v)) for v in col if not _is_missing(v)]
                levels = sorted(set(values))
                num_features = int(self.getNumFeatures())
                if len(levels) <= _MAX_ONE_HOT or num_features == 0:
                    # numFeatures=0 opts out of hashing entirely: index
                    kind = ("onehot" if self.getOneHotEncodeCategoricals()
                            and len(levels) <= _MAX_ONE_HOT else "index")
                    specs.append({"col": name, "kind": kind, "levels": levels})
                else:
                    dim = min(num_features, 4096)
                    specs.append({"col": name, "kind": "hash", "dim": dim})
        model = self._model_cls(specs=specs)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class FeaturizeModel(_FeaturizeParams, Model):
    def __init__(self, specs: Optional[List[Dict[str, Any]]] = None, **kwargs):
        super().__init__(**kwargs)
        self._specs = list(specs or [])

    @property
    def featureSpecs(self) -> List[Dict[str, Any]]:
        return [dict(s) for s in self._specs]

    def _transform(self, table: DataTable) -> DataTable:
        from .hashing import hash_term
        n = len(table)
        parts: List[np.ndarray] = []
        for spec in self._specs:
            col = table[spec["col"]]
            kind = spec["kind"]
            if kind == "vector":
                parts.append(col.reshape(n, -1).astype(np.float64))
            elif kind == "numeric":
                v = col.astype(np.float64)
                if self.getImputeMissing():
                    v = np.where(np.isnan(v), spec["mean"], v)
                parts.append(v[:, None])
            elif kind == "index":
                index = {lv: i for i, lv in enumerate(spec["levels"])}
                parts.append(np.asarray(
                    [index.get(str(_scalar(v)), -1) for v in col],
                    dtype=np.float64)[:, None])
            elif kind == "onehot":
                levels = spec["levels"]
                index = {lv: i for i, lv in enumerate(levels)}
                out = np.zeros((n, len(levels)))
                for r, v in enumerate(col):
                    i = index.get(str(_scalar(v)), -1)
                    if i >= 0:
                        out[r, i] = 1.0
                parts.append(out)
            elif kind == "hash":
                dim = spec["dim"]
                out = np.zeros((n, dim))
                for r, v in enumerate(col):
                    if not _is_missing(v):
                        out[r, hash_term(str(_scalar(v)), dim)] += 1.0
                parts.append(out)
            else:  # pragma: no cover
                raise ValueError(f"Unknown feature kind {kind!r}")
        features = (np.concatenate(parts, axis=1) if parts
                    else np.zeros((n, 0)))
        return table.withColumn(self.getOutputCol(), features)

    def _save_extra(self, path: str) -> None:
        serialize.save_json(path, "specs", self._specs)

    def _load_extra(self, path: str) -> None:
        self._specs = serialize.load_json(path, "specs")


class AssembleFeatures(Featurize):
    """Column assembly into a single vector; same engine as Featurize with
    hashing/one-hot decided identically (reference featurize/AssembleFeatures
    .scala — in the reference Featurize delegates here; in this build the
    shared engine lives in Featurize and AssembleFeatures is the alias)."""

    columnsToFeaturize = Param(
        "columnsToFeaturize", "Alias of inputCols", default=None,
        typeConverter=TypeConverters.toListString)

    def _fit(self, table: DataTable) -> "FeaturizeModel":
        cols = self._peek("columnsToFeaturize")
        if cols and not self.isSet("inputCols"):
            self.setInputCols(cols)
        return super()._fit(table)


class AssembleFeaturesModel(FeaturizeModel):
    """Alias model class for API parity."""


Featurize._model_cls = FeaturizeModel
AssembleFeatures._model_cls = AssembleFeaturesModel
