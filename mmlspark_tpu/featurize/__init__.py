"""Automated feature engineering (reference ``featurize/`` package).

Reference: src/main/scala/com/microsoft/ml/spark/featurize/ (expected path,
UNVERIFIED — SURVEY.md §2.1).  Auto-vectorization of mixed-type columns,
missing-data cleaning, value indexing, type conversion, and the text
featurization pipeline-in-a-box.
"""

from .featurize import (
    AssembleFeatures,
    AssembleFeaturesModel,
    CleanMissingData,
    CleanMissingDataModel,
    CountSelector,
    CountSelectorModel,
    DataConversion,
    Featurize,
    FeaturizeModel,
    IndexToValue,
    ValueIndexer,
    ValueIndexerModel,
)
from .text import (
    MultiNGram,
    PageSplitter,
    TextFeaturizer,
    TextFeaturizerModel,
)

__all__ = [
    "AssembleFeatures", "AssembleFeaturesModel",
    "CleanMissingData", "CleanMissingDataModel",
    "CountSelector", "CountSelectorModel",
    "DataConversion",
    "Featurize", "FeaturizeModel",
    "IndexToValue", "ValueIndexer", "ValueIndexerModel",
    "MultiNGram", "PageSplitter",
    "TextFeaturizer", "TextFeaturizerModel",
]
