"""Text featurization stages.

Reference: featurize/text/TextFeaturizer.scala, MultiNGram.scala,
PageSplitter.scala (expected paths, UNVERIFIED — SURVEY.md §2.1).

``TextFeaturizer`` is the reference's pipeline-in-a-box: tokenize →
(stopwords) → (n-grams) → hashingTF → IDF, collapsed here into one
estimator whose model applies the whole chain.  Hashing is murmur3-32 with
Spark's seed so indices match the reference bit-for-bit
(:mod:`mmlspark_tpu.featurize.hashing`).
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from ..core.params import HasInputCol, HasOutputCol, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import DataTable
from ..core import serialize
from .hashing import hash_terms

# english stop words (scikit-learn/Spark common subset, frozen here so the
# behavior never shifts under us)
_STOP_WORDS = frozenset("""
a about above after again against all am an and any are as at be because been
before being below between both but by could did do does doing down during
each few for from further had has have having he her here hers herself him
himself his how i if in into is it its itself just me more most my myself no
nor not now of off on once only or other our ours ourselves out over own same
she should so some such than that the their theirs them themselves then there
these they this those through to too under until up very was we were what when
where which while who whom why will with you your yours yourself yourselves
""".split())


class _TextParams(HasInputCol, HasOutputCol):
    tokenizerPattern = Param(
        "tokenizerPattern", "Regex the tokenizer splits on (gaps)",
        default=r"\s+", typeConverter=TypeConverters.toString)
    toLowercase = Param("toLowercase", "Lowercase before tokenizing",
                        default=True, typeConverter=TypeConverters.toBool)
    useStopWordsRemover = Param("useStopWordsRemover",
                                "Remove english stop words",
                                default=False,
                                typeConverter=TypeConverters.toBool)
    useNGram = Param("useNGram", "Emit n-grams instead of unigrams",
                     default=False, typeConverter=TypeConverters.toBool)
    nGramLength = Param("nGramLength", "n-gram length", default=2,
                        typeConverter=TypeConverters.toInt)
    numFeatures = Param("numFeatures", "Hashing dimension",
                        default=1 << 18, typeConverter=TypeConverters.toInt)
    binary = Param("binary", "Binary term counts", default=False,
                   typeConverter=TypeConverters.toBool)
    useIDF = Param("useIDF", "Rescale by inverse document frequency",
                   default=True, typeConverter=TypeConverters.toBool)
    minDocFreq = Param("minDocFreq", "Minimum document frequency for IDF",
                       default=1, typeConverter=TypeConverters.toInt)


def _tokenize(text: str, pattern: str, lower: bool) -> List[str]:
    if lower:
        text = text.lower()
    return [t for t in re.split(pattern, text.strip()) if t]


def _ngrams(tokens: List[str], n: int) -> List[str]:
    return [" ".join(tokens[i:i + n]) for i in range(len(tokens) - n + 1)]


class TextFeaturizer(_TextParams, Estimator):
    """Tokenize → stopwords → n-grams → hashingTF → IDF in one estimator."""

    def _terms(self, text: str) -> List[str]:
        toks = _tokenize(str(text), self.getTokenizerPattern(),
                         self.getToLowercase())
        if self.getUseStopWordsRemover():
            toks = [t for t in toks if t not in _STOP_WORDS]
        if self.getUseNGram():
            toks = _ngrams(toks, self.getNGramLength())
        return toks

    def _counts(self, text: str) -> np.ndarray:
        dim = self.getNumFeatures()
        vec = np.zeros(dim)
        idxs = hash_terms(self._terms(text), dim)
        for i in idxs:
            vec[i] += 1.0
        if self.getBinary():
            vec = (vec > 0).astype(np.float64)
        return vec

    def _fit(self, table: DataTable) -> "TextFeaturizerModel":
        texts = table[self.getInputCol()]
        dim = self.getNumFeatures()
        idf = None
        if self.getUseIDF():
            df = np.zeros(dim)
            for t in texts:
                idxs = np.unique(hash_terms(self._terms(t), dim))
                df[idxs] += 1.0
            n_docs = len(texts)
            df = np.where(df >= self.getMinDocFreq(), df, 0.0)
            # Spark IDF formula: log((m+1)/(df+1))
            idf = np.log((n_docs + 1.0) / (df + 1.0))
            idf = np.where(df > 0, idf, 0.0)
        model = TextFeaturizerModel(idf=idf)
        model.setParams(**{k: v for k, v in self._iterSetParams()})
        return model


class TextFeaturizerModel(_TextParams, Model):
    def __init__(self, idf: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self._idf = None if idf is None else np.asarray(idf)

    def _transform(self, table: DataTable) -> DataTable:
        helper = TextFeaturizer()
        helper._paramMap = dict(self._paramMap)
        texts = table[self.getInputCol()]
        rows = np.stack([helper._counts(t) for t in texts]) if len(texts) \
            else np.zeros((0, self.getNumFeatures()))
        if self._idf is not None:
            rows = rows * self._idf[None, :]
        return table.withColumn(self.getOutputCol(), rows)

    def _save_extra(self, path: str) -> None:
        if self._idf is not None:
            serialize.save_arrays(path, idf=self._idf)

    def _load_extra(self, path: str) -> None:
        import os
        self._idf = None
        if os.path.exists(os.path.join(path, "arrays.npz")):
            self._idf = serialize.load_arrays(path)["idf"]


class MultiNGram(HasInputCol, HasOutputCol, Transformer):
    """Emits the concatenation of n-grams for several lengths at once
    (reference featurize/text/MultiNGram.scala)."""

    lengths = Param("lengths", "The n-gram lengths to extract",
                    default=[1, 2, 3], typeConverter=TypeConverters.toListInt)

    def _transform(self, table: DataTable) -> DataTable:
        col = table[self.getInputCol()]
        out = np.empty(len(col), dtype=object)
        for r, tokens in enumerate(col):
            toks = list(tokens)
            grams: List[str] = []
            for n in self.getLengths():
                grams.extend(_ngrams(toks, n))
            out[r] = grams
        return table.withColumn(self.getOutputCol(), out)


class PageSplitter(HasInputCol, HasOutputCol, Transformer):
    """Splits long strings into pages within [min,max] character bounds,
    preferring whitespace boundaries (reference featurize/text/PageSplitter
    .scala — used to chunk documents for per-page cognitive calls)."""

    maximumPageLength = Param("maximumPageLength",
                              "Maximum number of characters per page",
                              default=5000, typeConverter=TypeConverters.toInt)
    minimumPageLength = Param(
        "minimumPageLength",
        "Minimum characters before a whitespace split is taken",
        default=4500, typeConverter=TypeConverters.toInt)
    boundaryRegex = Param("boundaryRegex", "Regex marking preferred breaks",
                          default=r"\s", typeConverter=TypeConverters.toString)

    def _split(self, text: str) -> List[str]:
        lo, hi = self.getMinimumPageLength(), self.getMaximumPageLength()
        pat = re.compile(self.getBoundaryRegex())
        pages = []
        s = str(text)
        while len(s) > hi:
            cut = hi
            for i in range(hi, lo - 1, -1):
                if pat.fullmatch(s[i - 1]):
                    cut = i
                    break
            pages.append(s[:cut])
            s = s[cut:]
        pages.append(s)
        return pages

    def _transform(self, table: DataTable) -> DataTable:
        col = table[self.getInputCol()]
        out = np.empty(len(col), dtype=object)
        for r, text in enumerate(col):
            out[r] = self._split(text)
        return table.withColumn(self.getOutputCol(), out)
