"""Ranking evaluation + adapter + train/validation split.

Reference: recommendation/RankingEvaluator.scala, RankingAdapter.scala,
RankingTrainValidationSplit.scala (expected paths, UNVERIFIED — SURVEY.md
§2.1).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.params import HasSeed, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import DataTable
from ..core import serialize

_METRICS = ("ndcgAt", "map", "precisionAtk", "recallAtK")


class RankingEvaluator:
    """NDCG@k / MAP / precision@k / recall@k over recommendation lists.

    ``evaluate`` takes a table with per-user ``recommendations`` (int array
    column, ranked) and ``groundTruth`` (object column of relevant item
    lists).  Not a Transformer in the reference either (it's an Evaluator),
    so it mirrors that shape.
    """

    def __init__(self, k: int = 10, metricName: str = "ndcgAt"):
        if metricName not in _METRICS:
            raise ValueError(f"Unknown metric {metricName!r}; "
                             f"choose from {_METRICS}")
        self.k = k
        self.metricName = metricName

    def setK(self, k: int) -> "RankingEvaluator":
        self.k = k
        return self

    def setMetricName(self, name: str) -> "RankingEvaluator":
        self.metricName = name
        return self

    def evaluate(self, table: DataTable,
                 recCol: str = "recommendations",
                 labelCol: str = "groundTruth") -> float:
        recs = table[recCol]
        truth = table[labelCol]
        vals = []
        for r, t in zip(recs, truth):
            r = list(np.asarray(r).tolist())[:self.k]
            t = set(np.asarray(t).tolist())
            if not t:
                continue
            vals.append(self._one(r, t))
        return float(np.mean(vals)) if vals else 0.0

    def _one(self, rec: List[int], truth: set) -> float:
        k = self.k
        hits = [1.0 if r in truth else 0.0 for r in rec]
        if self.metricName == "precisionAtk":
            return sum(hits) / k
        if self.metricName == "recallAtK":
            return sum(hits) / len(truth)
        if self.metricName == "map":
            score, n_hits = 0.0, 0
            for i, h in enumerate(hits):
                if h:
                    n_hits += 1
                    score += n_hits / (i + 1.0)
            return score / min(len(truth), k)
        # ndcgAt
        dcg = sum(h / np.log2(i + 2.0) for i, h in enumerate(hits))
        ideal = sum(1.0 / np.log2(i + 2.0)
                    for i in range(min(len(truth), k)))
        return dcg / ideal if ideal > 0 else 0.0


class RankingAdapter(Estimator):
    """Wraps a recommender estimator so fit→transform yields per-user
    ranked recommendation lists plus ground truth, ready for
    :class:`RankingEvaluator` (recommendation/RankingAdapter.scala)."""

    mode = Param("mode", "allUsers (only supported mode)", default="allUsers",
                 typeConverter=TypeConverters.toString)
    k = Param("k", "Recommendations per user", default=10,
              typeConverter=TypeConverters.toInt)
    minRatingsPerUser = Param("minRatingsPerUser",
                              "Drop users with fewer ratings", default=1,
                              typeConverter=TypeConverters.toInt)

    def __init__(self, recommender: Optional[Estimator] = None, **kwargs):
        super().__init__(**kwargs)
        self._recommender = recommender

    def getRecommender(self) -> Optional[Estimator]:
        return self._recommender

    def setRecommender(self, rec: Estimator) -> "RankingAdapter":
        self._recommender = rec
        return self

    def _save_extra(self, path: str) -> None:
        serialize.save_optional_stage(path, "recommender", self._recommender)

    def _load_extra(self, path: str) -> None:
        self._recommender = serialize.load_optional_stage(path,
                                                          "recommender")

    def _fit(self, table: DataTable) -> "RankingAdapterModel":
        fitted = self._recommender._fit(table)
        model = RankingAdapterModel(fitted=fitted)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class RankingAdapterModel(Model):
    mode = RankingAdapter.mode
    k = RankingAdapter.k
    minRatingsPerUser = RankingAdapter.minRatingsPerUser

    def __init__(self, fitted=None, **kwargs):
        super().__init__(**kwargs)
        self._fitted = fitted

    def getRecommenderModel(self):
        return self._fitted

    def _transform(self, table: DataTable) -> DataTable:
        user_col = self._fitted.getUserCol()
        item_col = self._fitted.getItemCol()
        recs = self._fitted.recommendForAllUsers(self.getK())
        users = np.asarray(table[user_col], dtype=np.int64)
        items = np.asarray(table[item_col], dtype=np.int64)
        truth: Dict[int, List[int]] = {}
        for u, i in zip(users, items):
            truth.setdefault(int(u), []).append(int(i))
        rec_users = np.asarray(recs[self._fitted.getUserCol()],
                               dtype=np.int64)
        gt = np.empty(len(rec_users), dtype=object)
        for r, u in enumerate(rec_users):
            gt[r] = truth.get(int(u), [])
        out = recs.withColumn("groundTruth", gt)
        min_ratings = self.getMinRatingsPerUser()
        if min_ratings > 1:
            keep = np.asarray([len(truth.get(int(u), [])) >= min_ratings
                               for u in rec_users])
            out = out.take(keep)
        return out

    def _save_extra(self, path: str) -> None:
        import os
        serialize.save_stage(self._fitted, os.path.join(path, "fitted"),
                             overwrite=True)

    def _load_extra(self, path: str) -> None:
        import os
        self._fitted = serialize.load_stage(os.path.join(path, "fitted"))


class RankingTrainValidationSplit(HasSeed, Estimator):
    """Per-user leave-out split + hyperparameter evaluation
    (recommendation/RankingTrainValidationSplit.scala)."""

    trainRatio = Param("trainRatio", "Per-user train fraction", default=0.75,
                       typeConverter=TypeConverters.toFloat)
    userCol = Param("userCol", "User column", default="user",
                    typeConverter=TypeConverters.toString)
    itemCol = Param("itemCol", "Item column", default="item",
                    typeConverter=TypeConverters.toString)
    k = Param("k", "Evaluation depth", default=10,
              typeConverter=TypeConverters.toInt)
    metricName = Param("metricName", "Ranking metric", default="ndcgAt",
                       typeConverter=TypeConverters.toString)

    def __init__(self, estimator: Optional[Estimator] = None,
                 estimatorParamMaps: Optional[Sequence[Dict]] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self._estimator = estimator
        self._param_maps = list(estimatorParamMaps or [{}])

    def _save_extra(self, path: str) -> None:
        serialize.save_optional_stage(path, "estimator", self._estimator)
        serialize.save_json(path, "param_maps", self._param_maps)

    def _load_extra(self, path: str) -> None:
        self._estimator = serialize.load_optional_stage(path, "estimator")
        try:
            self._param_maps = serialize.load_json(path, "param_maps")
        except FileNotFoundError:
            self._param_maps = [{}]

    def setEstimator(self, est: Estimator) -> "RankingTrainValidationSplit":
        self._estimator = est
        return self

    def setEstimatorParamMaps(self, maps) -> "RankingTrainValidationSplit":
        self._param_maps = list(maps)
        return self

    def _split(self, table: DataTable):
        users = np.asarray(table[self.getUserCol()], dtype=np.int64)
        rng = np.random.default_rng(self.getSeed())
        ratio = self.getTrainRatio()
        train_mask = np.zeros(len(users), dtype=bool)
        for u in np.unique(users):
            idx = np.flatnonzero(users == u)
            idx = rng.permutation(idx)
            cut = max(1, int(round(len(idx) * ratio)))
            train_mask[idx[:cut]] = True
        return table.take(train_mask), table.take(~train_mask)

    def _fit(self, table: DataTable) -> "RankingTrainValidationSplitModel":
        if self._estimator is None:
            raise ValueError("RankingTrainValidationSplit needs an estimator")
        train, val = self._split(table)
        evaluator = RankingEvaluator(k=self.getK(),
                                     metricName=self.getMetricName())
        best_metric, best_params = -np.inf, {}
        metrics = []
        for params in self._param_maps:
            cand = self._estimator.copy(
                {k: v for k, v in params.items()
                 if self._estimator.hasParam(k)})
            adapter = RankingAdapter(recommender=cand, k=self.getK())
            fitted = adapter._fit(train)
            scored = fitted._transform(val)
            m = evaluator.evaluate(scored)
            metrics.append(m)
            if m > best_metric:
                best_metric, best_params = m, dict(params)
        final = self._estimator.copy(
            {k: v for k, v in best_params.items()
             if self._estimator.hasParam(k)})._fit(table)
        model = RankingTrainValidationSplitModel(
            bestModel=final, validationMetrics=metrics)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class RankingTrainValidationSplitModel(Model):
    def __init__(self, bestModel=None,
                 validationMetrics: Optional[List[float]] = None, **kwargs):
        super().__init__(**kwargs)
        self._best = bestModel
        self._metrics = list(validationMetrics or [])

    def getBestModel(self):
        return self._best

    @property
    def validationMetrics(self) -> List[float]:
        return list(self._metrics)

    def _transform(self, table: DataTable) -> DataTable:
        return self._best._transform(table)

    def _save_extra(self, path: str) -> None:
        import os
        serialize.save_stage(self._best, os.path.join(path, "best"),
                             overwrite=True)
        serialize.save_json(path, "metrics", self._metrics)

    def _load_extra(self, path: str) -> None:
        import os
        self._best = serialize.load_stage(os.path.join(path, "best"))
        self._metrics = serialize.load_json(path, "metrics")
