"""RecommendationIndexer — string user/item ids → dense int indices.

Reference: recommendation/RecommendationIndexer.scala (expected path,
UNVERIFIED — SURVEY.md §2.1).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ..core.params import Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..core.schema import DataTable
from ..core import serialize


class RecommendationIndexer(Estimator):
    userInputCol = Param("userInputCol", "Raw user column",
                         typeConverter=TypeConverters.toString)
    userOutputCol = Param("userOutputCol", "Indexed user column",
                          typeConverter=TypeConverters.toString)
    itemInputCol = Param("itemInputCol", "Raw item column",
                         typeConverter=TypeConverters.toString)
    itemOutputCol = Param("itemOutputCol", "Indexed item column",
                          typeConverter=TypeConverters.toString)
    ratingCol = Param("ratingCol", "Rating column", default="rating",
                      typeConverter=TypeConverters.toString)

    def _fit(self, table: DataTable) -> "RecommendationIndexerModel":
        users = sorted({str(v) for v in table[self.getUserInputCol()]})
        items = sorted({str(v) for v in table[self.getItemInputCol()]})
        model = RecommendationIndexerModel(userLevels=users, itemLevels=items)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class RecommendationIndexerModel(Model):
    userInputCol = RecommendationIndexer.userInputCol
    userOutputCol = RecommendationIndexer.userOutputCol
    itemInputCol = RecommendationIndexer.itemInputCol
    itemOutputCol = RecommendationIndexer.itemOutputCol
    ratingCol = RecommendationIndexer.ratingCol

    def __init__(self, userLevels: Optional[List[Any]] = None,
                 itemLevels: Optional[List[Any]] = None, **kwargs):
        super().__init__(**kwargs)
        self._users = list(userLevels or [])
        self._items = list(itemLevels or [])

    @property
    def userLevels(self) -> List[Any]:
        return list(self._users)

    @property
    def itemLevels(self) -> List[Any]:
        return list(self._items)

    def _transform(self, table: DataTable) -> DataTable:
        u_index = {v: i for i, v in enumerate(self._users)}
        i_index = {v: i for i, v in enumerate(self._items)}
        u = np.asarray([u_index.get(str(v), -1)
                        for v in table[self.getUserInputCol()]],
                       dtype=np.int64)
        it = np.asarray([i_index.get(str(v), -1)
                         for v in table[self.getItemInputCol()]],
                        dtype=np.int64)
        return table.withColumns({self.getUserOutputCol(): u,
                                  self.getItemOutputCol(): it})

    def recoverUser(self, idx: np.ndarray) -> np.ndarray:
        return np.asarray([self._users[i] for i in idx], dtype=object)

    def recoverItem(self, idx: np.ndarray) -> np.ndarray:
        return np.asarray([self._items[i] for i in idx], dtype=object)

    def _save_extra(self, path: str) -> None:
        serialize.save_json(path, "levels",
                            {"users": self._users, "items": self._items})

    def _load_extra(self, path: str) -> None:
        levels = serialize.load_json(path, "levels")
        self._users = levels["users"]
        self._items = levels["items"]
