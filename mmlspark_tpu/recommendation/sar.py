"""SAR — Smart Adaptive Recommendations, TPU-native.

Reference: recommendation/SAR.scala, recommendation/SARModel.scala (expected
paths, UNVERIFIED — SURVEY.md §2.1).

The algorithm is two matmuls — exactly what the MXU wants:

* **Item similarity**: co-occurrence ``C = Aᵀ A`` over the binarized
  user×item interaction matrix, then jaccard / lift / co-occurrence
  normalization (elementwise on device).
* **User affinity**: time-decayed rating sum per (user, item).
* **Score**: ``S = affinity @ similarity``; seen items optionally masked;
  top-k via ``lax.top_k``.

The reference computes C with Spark joins; a dense device matmul replaces
the whole shuffle plan.  Dense user×item is the honest TPU design for the
catalog sizes SAR targets (items ≤ ~100k; users stream through in batches).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import HasSeed, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..core.schema import DataTable
from ..core import serialize


class _SARParams(HasSeed):
    userCol = Param("userCol", "User id column (int indices)",
                    default="user", typeConverter=TypeConverters.toString)
    itemCol = Param("itemCol", "Item id column (int indices)",
                    default="item", typeConverter=TypeConverters.toString)
    ratingCol = Param("ratingCol", "Rating column", default="rating",
                      typeConverter=TypeConverters.toString)
    timeCol = Param("timeCol", "Timestamp column for affinity decay "
                    "(optional)", default=None,
                    typeConverter=TypeConverters.toString)
    supportThreshold = Param("supportThreshold",
                             "Minimum co-occurrence count", default=4,
                             typeConverter=TypeConverters.toInt)
    similarityFunction = Param(
        "similarityFunction", "jaccard | lift | cooccurrence",
        default="jaccard", typeConverter=TypeConverters.toString,
        validator=lambda v: v in ("jaccard", "lift", "cooccurrence"))
    timeDecayCoeff = Param("timeDecayCoeff", "Half-life in days",
                           default=30, typeConverter=TypeConverters.toInt)
    allowSeedItemsInRecommendations = Param(
        "allowSeedItemsInRecommendations",
        "Keep already-seen items in recommendations", default=True,
        typeConverter=TypeConverters.toBool)


@partial(jax.jit, static_argnames=("sim_fn",))
def _similarity(A, support_threshold, sim_fn: str):
    """Item-item similarity from binarized interactions A (users × items)."""
    C = A.T @ A  # co-occurrence counts — one MXU matmul
    diag = jnp.diag(C)
    C = jnp.where(C >= support_threshold, C, 0.0)
    if sim_fn == "jaccard":
        denom = diag[:, None] + diag[None, :] - C
        S = jnp.where(denom > 0, C / jnp.maximum(denom, 1e-12), 0.0)
    elif sim_fn == "lift":
        denom = diag[:, None] * diag[None, :]
        S = jnp.where(denom > 0, C / jnp.maximum(denom, 1e-12), 0.0)
    else:
        S = C
    return S


@jax.jit
def _score(affinity, similarity):
    return affinity @ similarity


class SAR(_SARParams, Estimator):
    """Item-item similarity recommender (recommendation/SAR.scala)."""

    def _fit(self, table: DataTable) -> "SARModel":
        users = np.asarray(table[self.getUserCol()], dtype=np.int64)
        items = np.asarray(table[self.getItemCol()], dtype=np.int64)
        if len(users) and (users.min() < 0 or items.min() < 0):
            raise ValueError(
                "Negative user/item ids in fitting data (unseen ids from "
                "RecommendationIndexer map to -1); filter them before fit")
        ratings = (np.asarray(table[self.getRatingCol()], dtype=np.float64)
                   if self.getRatingCol() in table
                   else np.ones(len(users)))
        n_users = int(users.max()) + 1 if len(users) else 0
        n_items = int(items.max()) + 1 if len(items) else 0

        # binarized interaction matrix for similarity
        A = np.zeros((n_users, n_items), dtype=np.float32)
        A[users, items] = 1.0

        # time-decayed affinity
        time_col = self.getTimeCol()
        if time_col and time_col in table:
            t = np.asarray(table[time_col], dtype=np.float64)
            t_ref = t.max()
            half_life_s = self.getTimeDecayCoeff() * 86400.0
            decay = np.power(0.5, (t_ref - t) / half_life_s)
        else:
            decay = np.ones(len(users))
        affinity = np.zeros((n_users, n_items), dtype=np.float32)
        np.add.at(affinity, (users, items), ratings * decay)

        S = np.asarray(_similarity(
            jnp.asarray(A), jnp.asarray(float(self.getSupportThreshold())),
            self.getSimilarityFunction()))
        model = SARModel(similarity=S, affinity=affinity, seen=A)
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class SARModel(_SARParams, Model):
    def __init__(self, similarity: Optional[np.ndarray] = None,
                 affinity: Optional[np.ndarray] = None,
                 seen: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self._sim = similarity
        self._aff = affinity
        self._seen = seen

    @property
    def itemSimilarity(self) -> np.ndarray:
        return self._sim.copy()

    @property
    def userAffinity(self) -> np.ndarray:
        return self._aff.copy()

    def _transform(self, table: DataTable) -> DataTable:
        """Scores each (user, item) row: affinity·similarity[:, item]."""
        users = np.asarray(table[self.getUserCol()], dtype=np.int64)
        items = np.asarray(table[self.getItemCol()], dtype=np.int64)
        n_users, n_items = self._aff.shape[0], self._sim.shape[0]
        known = ((users >= 0) & (users < n_users)
                 & (items >= 0) & (items < n_items))
        pred = np.zeros(len(users))  # cold-start ids score 0, never wrap
        # score only the users present in the batch, not the full matrix
        uniq, inverse = np.unique(users[known], return_inverse=True)
        if len(uniq):
            sub_scores = np.asarray(_score(
                jnp.asarray(self._aff[uniq]), jnp.asarray(self._sim)))
            pred[known] = sub_scores[inverse, items[known]]
        return table.withColumn("prediction", pred.astype(np.float64))

    def recommendForAllUsers(self, numItems: int) -> DataTable:
        scores = _score(jnp.asarray(self._aff), jnp.asarray(self._sim))
        if not self.getAllowSeedItemsInRecommendations():
            scores = jnp.where(jnp.asarray(self._seen) > 0, -jnp.inf, scores)
        top_scores, top_items = jax.lax.top_k(
            scores, min(numItems, scores.shape[1]))
        return DataTable({
            self.getUserCol(): np.arange(scores.shape[0], dtype=np.int64),
            "recommendations": np.asarray(top_items, dtype=np.int64),
            "ratings": np.asarray(top_scores, dtype=np.float64),
        })

    def recommendForUserSubset(self, users: np.ndarray,
                               numItems: int) -> DataTable:
        users = np.asarray(users, dtype=np.int64)
        n_users, n_items = self._aff.shape
        valid = (users >= 0) & (users < n_users)
        k = min(numItems, n_items)
        # score only the requested users; unknown/cold-start ids get empty
        # recommendations instead of wrapping to another user's row
        items_out = np.full((len(users), k), -1, dtype=np.int64)
        ratings_out = np.zeros((len(users), k))
        if valid.any():
            scores = _score(jnp.asarray(self._aff[users[valid]]),
                            jnp.asarray(self._sim))
            if not self.getAllowSeedItemsInRecommendations():
                scores = jnp.where(
                    jnp.asarray(self._seen[users[valid]]) > 0,
                    -jnp.inf, scores)
            top_scores, top_items = jax.lax.top_k(scores, k)
            items_out[valid] = np.asarray(top_items)
            ratings_out[valid] = np.asarray(top_scores)
        return DataTable({
            self.getUserCol(): users,
            "recommendations": items_out,
            "ratings": ratings_out,
        })

    def _save_extra(self, path: str) -> None:
        serialize.save_arrays(path, similarity=self._sim,
                              affinity=self._aff, seen=self._seen)

    def _load_extra(self, path: str) -> None:
        arrays = serialize.load_arrays(path)
        self._sim = arrays["similarity"]
        self._aff = arrays["affinity"]
        self._seen = arrays["seen"]
