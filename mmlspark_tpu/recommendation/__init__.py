"""Recommendation (reference ``recommendation/`` package).

Reference: src/main/scala/com/microsoft/ml/spark/recommendation/ (expected
paths, UNVERIFIED — SURVEY.md §2.1): SAR (Smart Adaptive Recommendations)
item-item recommender, RecommendationIndexer, RankingEvaluator,
RankingAdapter, RankingTrainValidationSplit.
"""

from .sar import SAR, SARModel
from .indexer import RecommendationIndexer, RecommendationIndexerModel
from .ranking import (
    RankingAdapter,
    RankingAdapterModel,
    RankingEvaluator,
    RankingTrainValidationSplit,
    RankingTrainValidationSplitModel,
)

__all__ = [
    "SAR", "SARModel",
    "RecommendationIndexer", "RecommendationIndexerModel",
    "RankingAdapter", "RankingAdapterModel", "RankingEvaluator",
    "RankingTrainValidationSplit", "RankingTrainValidationSplitModel",
]
