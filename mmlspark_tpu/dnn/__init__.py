from .model import DNNModel, ResNetFeaturizerModel, CNTKModel
from .resnet import ResNet, build_resnet, init_params, load_torch_state_dict

__all__ = ["DNNModel", "ResNetFeaturizerModel", "CNTKModel", "ResNet",
           "build_resnet", "init_params", "load_torch_state_dict"]
