"""CNTK-v2 model format: protobuf Dictionary reader/writer + jax evaluator.

The reference's CNTKModel evaluates serialized CNTK-v2 ``.model`` files
(cntk/CNTKModel.scala, expected path, UNVERIFIED — SURVEY.md §2.2 row 2),
including *layer surgery* (cutting the graph at a named node).  This module
implements that capability natively:

* the **wire format** — CNTK v2 serializes a ``Dictionary`` protobuf
  (public schema ``Source/CNTKv2LibraryDll/proto/CNTK.proto``): nested
  ``DictionaryValue`` oneofs over bool/int/size_t/float/double/string/
  NDShape/Axis/Vector/Dictionary/NDArrayView.  Field numbers below follow
  that public schema; like the LightGBM text golden
  (tests/golden/), the writer and reader are hand-built from the spec and
  round-trip-verified against each other — a stock-CNTK cross-check
  requires a network-enabled session and stays on the queue.
* the **graph layer** — a serialized ``CompositeFunction`` dictionary
  (``root`` uid, ``functions`` vector of primitive functions, ``inputs``
  vector of variables with parameter/constant NDArrayView payloads);
* a **jax evaluator** for the primitive-op subset that covers MLP and
  CNN inference graphs (Times, Plus, Minus, ElementTimes, ReLU, Sigmoid,
  Tanh, Softmax, Reshape, Convolution, Pooling, BatchNormalization,
  Combine), with ``output_node`` selecting any intermediate function —
  the reference's layer-surgery contract.

Tensor conventions in this build's evaluator: batch axis leading; image
tensors ``(C, H, W)`` per sample; convolution kernels ``(C_out, C_in,
KH, KW)``; ``Times(a, b)`` contracts ``a``'s last axis with ``b``'s
first (CNTK's static-shape semantics).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..onnx.proto import (_field, _len_field, _varint, packed_floats,
                          packed_varints, parse)

# ---- DictionaryValue oneof field numbers (public CNTK.proto) --------------

_DV_BOOL, _DV_INT, _DV_SIZET, _DV_FLOAT, _DV_DOUBLE = 2, 3, 4, 5, 6
_DV_STRING, _DV_NDSHAPE, _DV_AXIS, _DV_VECTOR = 7, 8, 9, 10
_DV_DICT, _DV_NDARRAY = 11, 12

#: PrimitiveOpType values used by this build (public PrimitiveOpType.h
#: declaration order).  Only the subset the evaluator implements.
OPS = {
    "Sigmoid": 1, "Tanh": 2, "ReLU": 3, "Softmax": 10, "Reshape": 16,
    "Pooling": 17, "Plus": 19, "Minus": 20, "ElementTimes": 21,
    "Times": 31, "Convolution": 33, "BatchNormalization": 40,
    "Splice": 43, "Combine": 44,
}
_OP_NAME = {v: k for k, v in OPS.items()}

# Variable kinds (CNTK VariableKind)
KIND_INPUT, KIND_OUTPUT, KIND_PARAMETER, KIND_CONSTANT = 0, 1, 2, 3


# ---- writer ---------------------------------------------------------------

def _enc_ndshape(dims) -> bytes:
    payload = b"".join(_varint(int(d)) for d in dims)
    return _len_field(1, payload)        # packed repeated uint64


def _enc_ndarrayview(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr, np.float32)
    out = _field(1, 0, _varint(1))       # data_type = Float
    out += _field(2, 0, _varint(0))      # storage_format = Dense
    out += _len_field(3, _enc_ndshape(arr.shape))
    vals = _len_field(1, np.ascontiguousarray(
        arr.reshape(-1)).astype("<f4").tobytes())
    out += _len_field(4, vals)           # FloatValues{ packed value=1 }
    return out


def _enc_value(v) -> bytes:
    out = _field(1, 0, _varint(1))       # version
    if isinstance(v, bool):
        out += _field(_DV_BOOL, 0, _varint(1 if v else 0))
    elif isinstance(v, (int, np.integer)):
        if v >= 0:
            out += _field(_DV_SIZET, 0, _varint(int(v)))
        else:
            # negative ints ride the signed int32 field as the standard
            # 64-bit two's-complement varint (an unmasked negative would
            # never terminate _varint)
            out += _field(_DV_INT, 0,
                          _varint(int(v) & ((1 << 64) - 1)))
    elif isinstance(v, float):
        import struct
        out += _field(_DV_DOUBLE, 1, struct.pack("<d", v))
    elif isinstance(v, str):
        out += _len_field(_DV_STRING, v.encode("utf-8"))
    elif isinstance(v, tuple):           # NDShape spelled as a tuple
        out += _len_field(_DV_NDSHAPE, _enc_ndshape(v))
    elif isinstance(v, list):            # Vector
        payload = b"".join(_len_field(1, _enc_value(x)) for x in v)
        out += _len_field(_DV_VECTOR, payload)
    elif isinstance(v, dict):
        out += _len_field(_DV_DICT, _enc_dict(v))
    elif isinstance(v, np.ndarray):
        out += _len_field(_DV_NDARRAY, _enc_ndarrayview(v))
    else:
        raise TypeError(f"cannot serialize {type(v)} into a CNTK "
                        "DictionaryValue")
    return out


def _enc_dict(d: Dict[str, Any]) -> bytes:
    out = _field(1, 0, _varint(1))       # version
    for k, v in d.items():
        entry = _len_field(1, k.encode("utf-8")) \
            + _len_field(2, _enc_value(v))
        out += _len_field(2, entry)      # map<string, DictionaryValue>
    return out


def save_model_dict(path: str, model: Dict[str, Any]) -> None:
    with open(path, "wb") as fh:
        fh.write(_enc_dict(model))


# ---- reader ---------------------------------------------------------------

def _dec_ndshape(raw) -> Tuple[int, ...]:
    return tuple(int(d) for d in packed_varints(parse(raw).get(1, [])))


def _dec_ndarrayview(raw) -> np.ndarray:
    f = parse(raw)
    shape = _dec_ndshape(f[3][0]) if 3 in f else ()
    if 4 in f:       # FloatValues
        vals = packed_floats(parse(f[4][0]).get(1, []))
        return np.asarray(vals, np.float32).reshape(shape)
    if 5 in f:       # DoubleValues
        inner = parse(f[5][0]).get(1, [])
        out = np.concatenate([
            np.frombuffer(bytes(v), "<f8") for v in inner]) \
            if inner else np.zeros(0)
        return out.astype(np.float64).reshape(shape)
    return np.zeros(shape, np.float32)


def _dec_value(raw):
    import struct
    f = parse(raw)
    if _DV_BOOL in f:
        return bool(f[_DV_BOOL][0])
    if _DV_INT in f:
        x = int(f[_DV_INT][0])
        return x - (1 << 64) if x >= (1 << 63) else x
    if _DV_SIZET in f:
        return int(f[_DV_SIZET][0])
    if _DV_FLOAT in f:
        return struct.unpack("<f", bytes(f[_DV_FLOAT][0]))[0]
    if _DV_DOUBLE in f:
        return struct.unpack("<d", bytes(f[_DV_DOUBLE][0]))[0]
    if _DV_STRING in f:
        return bytes(f[_DV_STRING][0]).decode("utf-8")
    if _DV_NDSHAPE in f:
        return _dec_ndshape(f[_DV_NDSHAPE][0])
    if _DV_VECTOR in f:
        return [_dec_value(x)
                for x in parse(f[_DV_VECTOR][0]).get(1, [])]
    if _DV_DICT in f:
        return _dec_dict(f[_DV_DICT][0])
    if _DV_NDARRAY in f:
        return _dec_ndarrayview(f[_DV_NDARRAY][0])
    return None


def _dec_dict(raw) -> Dict[str, Any]:
    f = parse(raw)
    out: Dict[str, Any] = {}
    for entry in f.get(2, []):
        ef = parse(entry)
        key = bytes(ef[1][0]).decode("utf-8")
        out[key] = _dec_value(ef[2][0])
    return out


def load_model_dict(path: str) -> Dict[str, Any]:
    with open(path, "rb") as fh:
        return _dec_dict(fh.read())


def looks_like_cntk_model(path: str) -> bool:
    """Sniff: a CNTK v2 .model parses as a Dictionary whose map contains
    the CompositeFunction keys."""
    try:
        d = load_model_dict(path)
        return d.get("type") == "CompositeFunction" and "functions" in d
    except Exception:  # noqa: BLE001 - any parse failure = not CNTK
        return False


# ---- graph builder (fixture authoring + CNTK-format export) ---------------

class GraphBuilder:
    """Author a CompositeFunction dictionary programmatically."""

    def __init__(self):
        self._vars: List[Dict[str, Any]] = []
        self._funcs: List[Dict[str, Any]] = []
        self._n = 0

    def _uid(self, prefix: str) -> str:
        self._n += 1
        return f"{prefix}{self._n}"

    def input(self, shape, name="features") -> str:
        uid = self._uid("Input")
        self._vars.append({"type": "Variable", "uid": uid, "name": name,
                           "kind": KIND_INPUT, "data_type": 1,
                           "shape": tuple(shape)})
        return uid

    def parameter(self, array: np.ndarray, name: str = "") -> str:
        uid = self._uid("Parameter")
        self._vars.append({"type": "Variable", "uid": uid, "name": name,
                           "kind": KIND_PARAMETER, "data_type": 1,
                           "shape": tuple(np.shape(array)),
                           "value": np.asarray(array, np.float32)})
        return uid

    def op(self, op_name: str, inputs: List[str], name: str = "",
           **attrs) -> str:
        uid = self._uid(op_name)
        self._funcs.append({
            "type": "PrimitiveFunction", "uid": uid, "name": name,
            "op": OPS[op_name], "inputs": list(inputs),
            "attributes": dict(attrs)})
        return uid

    def model(self, root: str) -> Dict[str, Any]:
        return {"version": 1, "type": "CompositeFunction", "root": root,
                "functions": list(self._funcs), "inputs": list(self._vars)}

    def save(self, path: str, root: str) -> None:
        save_model_dict(path, self.model(root))


# ---- jax evaluator --------------------------------------------------------

def build_eval(model: Dict[str, Any],
               output_node: Optional[str] = None):
    """Compile the CompositeFunction into ``(apply_fn, params)``.

    ``apply_fn(params, batch)`` evaluates the graph with the batch axis
    leading; ``params`` maps parameter uid → array (a pytree, so the
    generic DNNModel minibatch/bf16 machinery applies).  ``output_node``
    cuts the graph at the function whose *name* or *uid* matches — the
    reference CNTKModel's layer surgery."""
    import jax.numpy as jnp
    from jax import lax

    var_by_uid = {v["uid"]: v for v in model["inputs"]}
    funcs = model["functions"]
    fn_by_uid = {f["uid"]: f for f in funcs}
    params = {v["uid"]: np.asarray(v["value"], np.float32)
              for v in model["inputs"]
              if v["kind"] in (KIND_PARAMETER, KIND_CONSTANT)}

    root = model["root"]
    if output_node:
        matches = [f["uid"] for f in funcs
                   if f["uid"] == output_node or f["name"] == output_node]
        if not matches:
            names = sorted({f["name"] or f["uid"] for f in funcs})
            raise ValueError(
                f"output node {output_node!r} not found; graph nodes: "
                f"{names}")
        root = matches[0]

    input_uids = [v["uid"] for v in model["inputs"]
                  if v["kind"] == KIND_INPUT]
    if len(input_uids) != 1:
        raise ValueError(
            f"expected exactly one input variable, found {len(input_uids)}")
    input_uid = input_uids[0]

    def apply_fn(params, batch):
        cache: Dict[str, Any] = {input_uid: batch}

        def ev(uid):
            if uid in cache:
                return cache[uid]
            if uid in params:
                return jnp.asarray(params[uid])
            if uid in var_by_uid:      # parameter stripped? shouldn't happen
                raise KeyError(f"variable {uid} has no value")
            f = fn_by_uid[uid]
            ins = [ev(i) for i in f["inputs"]]
            a = f.get("attributes", {})
            op = _OP_NAME.get(f["op"])
            if op == "Times":
                out = jnp.tensordot(ins[0], ins[1], axes=([-1], [0]))
            elif op == "Plus":
                out = ins[0] + ins[1]
            elif op == "Minus":
                out = ins[0] - ins[1]
            elif op == "ElementTimes":
                out = ins[0] * ins[1]
            elif op == "ReLU":
                out = jnp.maximum(ins[0], 0)
            elif op == "Sigmoid":
                out = 1.0 / (1.0 + jnp.exp(-ins[0]))
            elif op == "Tanh":
                out = jnp.tanh(ins[0])
            elif op == "Softmax":
                out = jnp.exp(ins[0] - jnp.max(ins[0], -1, keepdims=True))
                out = out / jnp.sum(out, -1, keepdims=True)
            elif op == "Reshape":
                shape = tuple(int(d) for d in a["newShape"])
                out = ins[0].reshape((ins[0].shape[0],) + shape)
            elif op == "Convolution":
                # kernel (C_out, C_in, KH, KW); data (N, C, H, W)
                strides = tuple(int(s) for s in a.get("strides", (1, 1)))
                ap = a.get("autoPadding", True)
                if isinstance(ap, (list, tuple)):
                    # CNTK spells autoPadding per dimension; [False,
                    # False] must select VALID, not truthy-SAME
                    ap = any(bool(x) for x in ap)
                pad = "SAME" if ap else "VALID"
                out = lax.conv_general_dilated(
                    ins[1], ins[0], window_strides=strides, padding=pad,
                    dimension_numbers=("NCHW", "OIHW", "NCHW"))
            elif op == "Pooling":
                w = tuple(int(s) for s in a["poolingWindowShape"])
                strides = tuple(int(s) for s in a.get("strides", w))
                kind = int(a.get("poolingType", 0))
                window = (1, 1) + w
                st = (1, 1) + strides
                if kind == 0:
                    out = lax.reduce_window(
                        ins[0], -jnp.inf, lax.max, window, st, "VALID")
                else:
                    out = lax.reduce_window(
                        ins[0], 0.0, lax.add, window, st, "VALID") \
                        / float(np.prod(w))
            elif op == "BatchNormalization":
                # inputs: x, scale, bias, run_mean, run_variance
                x, scale, bias, mean, var = ins[:5]
                eps = float(a.get("epsilon", 1e-5))
                shp = (1, -1) + (1,) * (x.ndim - 2)
                out = (x - mean.reshape(shp)) \
                    * (scale.reshape(shp)
                       / jnp.sqrt(var.reshape(shp) + eps)) \
                    + bias.reshape(shp)
            elif op == "Splice":
                out = jnp.concatenate(ins, axis=int(a.get("axis", -1)))
            elif op == "Combine":
                out = ins[0] if len(ins) == 1 else tuple(ins)
            else:
                raise NotImplementedError(
                    f"CNTK op {f['op']} ({op or 'unknown'}) is not in "
                    f"this build's evaluator subset: {sorted(OPS)}")
            cache[uid] = out
            return out

        return ev(root)

    return apply_fn, params
