"""DNN inference transformers: the CNTKModel/TorchModel analog.

TPU-native re-design of the reference's ``CNTKModel`` (cntk/CNTKModel.scala,
expected path, UNVERIFIED; SURVEY.md §3.3): the reference broadcasts CNTK
model bytes and evals minibatches over JNI per executor; here a flax/jax
apply function is jitted once per input shape and minibatches stream through
it on the TPU.  Fixed-size minibatches with tail padding keep a single
compiled program (no per-batch recompiles) — the moral equivalent of the
reference pairing ``MiniBatchTransformer`` with its JNI eval loop.
"""

from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import Param, TypeConverters, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.schema import DataTable


class DNNModel(Transformer, HasInputCol, HasOutputCol):
    """Runs a jitted apply function over minibatches of a column.

    ``apply_fn(variables, batch) -> outputs``; set via constructor or
    :meth:`setModel`.  Subclasses provide architecture-specific loading.
    """

    miniBatchSize = Param("miniBatchSize", "Rows per device minibatch",
                          default=64, typeConverter=TypeConverters.toInt)
    computeDtype = Param(
        "computeDtype",
        "Device compute dtype: 'float32' or 'bfloat16'.  bfloat16 halves "
        "HBM traffic and doubles MXU throughput (weights and activations "
        "cast on device; outputs always return as float32) — the idiomatic "
        "TPU inference mode for featurization, where last-bit parity "
        "doesn't matter", default="float32",
        typeConverter=TypeConverters.toString)

    def __init__(self, apply_fn: Optional[Callable] = None,
                 variables: Any = None, **kwargs):
        super().__init__(**kwargs)
        self._apply_fn = apply_fn
        self._variables = variables
        self._jitted = None
        self._jitted_dtype = None
        self._cast_variables = None

    def setModel(self, apply_fn: Callable, variables: Any) -> "DNNModel":
        self._apply_fn = apply_fn
        self._variables = variables
        self._jitted = None
        self._cast_variables = None
        return self

    def _get_jitted(self):
        dt = self.getComputeDtype()
        if self._jitted is None or self._jitted_dtype != dt:
            if self._apply_fn is None:
                raise ValueError(
                    f"{type(self).__name__} has no model; call setModel() or "
                    "construct with apply_fn/variables")
            if dt == "bfloat16":
                base = self._apply_fn

                def bf16_fn(variables, batch):
                    out = base(variables, batch.astype(jnp.bfloat16))
                    return jax.tree_util.tree_map(
                        lambda a: a.astype(jnp.float32), out)

                self._jitted = jax.jit(bf16_fn)
            elif dt == "float32":
                self._jitted = jax.jit(self._apply_fn)
            else:
                raise ValueError(
                    f"computeDtype must be 'float32' or 'bfloat16', got "
                    f"{dt!r}")
            self._jitted_dtype = dt
            self._cast_variables = None
        return self._jitted

    def _exec_variables(self):
        """Weights in the compute dtype, cast ONCE and cached — a per-batch
        in-jit cast would re-read the full f32 tree from HBM every launch,
        forfeiting the bf16 traffic saving."""
        if self.getComputeDtype() != "bfloat16":
            return self._variables
        if self._cast_variables is None:
            self._cast_variables = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
                self._variables)
        return self._cast_variables

    def _batch_input(self, col: np.ndarray) -> np.ndarray:
        if col.dtype == object:
            col = np.stack([np.asarray(x, np.float32) for x in col])
        return np.asarray(col, np.float32)

    def _transform(self, table: DataTable) -> DataTable:
        col = self._batch_input(table[self.getInputCol()])
        n = col.shape[0]
        bs = self.getMiniBatchSize()
        fn = self._get_jitted()
        # dispatch minibatches asynchronously with a bounded in-flight
        # window: upload of batch k+1 overlaps compute of batch k (a
        # per-batch np.asarray would serialize each launch behind a device
        # round-trip — ~ms of dead time per minibatch on a tunneled TPU),
        # while draining past the window keeps pinned input buffers at
        # O(window · batch) HBM instead of O(dataset)
        window = 4
        variables = self._exec_variables()
        outs, pending = [], []

        def drain_one():
            dev, p = pending.pop(0)
            o = np.asarray(dev)
            outs.append(o[:bs - p] if p else o)

        for start in range(0, n, bs):
            batch = col[start:start + bs]
            pad = bs - batch.shape[0]
            if pad:  # pad the tail so every minibatch hits the same program
                batch = np.concatenate(
                    [batch, np.zeros((pad,) + batch.shape[1:], batch.dtype)])
            pending.append((fn(variables, jnp.asarray(batch)), pad))
            if len(pending) > window:
                drain_one()
        while pending:
            drain_one()
        result = np.concatenate(outs, axis=0) if outs else \
            np.zeros((0, 0), np.float32)
        return table.withColumn(self.getOutputCol(),
                                result.astype(np.float64))

    # persistence: pickle the variable pytree; the apply_fn is rebuilt by
    # subclasses (generic DNNModel can't serialize arbitrary callables)
    def _save_extra(self, path: str) -> None:
        with open(os.path.join(path, "variables.pkl"), "wb") as f:
            pickle.dump(jax.device_get(self._variables), f)

    def _load_extra(self, path: str) -> None:
        p = os.path.join(path, "variables.pkl")
        self._jitted = None
        self._apply_fn = None
        if os.path.exists(p):
            with open(p, "rb") as f:
                self._variables = pickle.load(f)
        self._rebuild_apply_fn()

    def _rebuild_apply_fn(self) -> None:
        """Subclasses restore self._apply_fn after load."""


class ResNetFeaturizerModel(DNNModel):
    """Headless/classifier ResNet forward (the ImageFeaturizer engine)."""

    modelName = Param("modelName", "ResNet variant", default="resnet50",
                      typeConverter=TypeConverters.toString)
    cutOutputLayers = Param("cutOutputLayers",
                            "1 -> pooled features (headless), 0 -> logits",
                            default=1, typeConverter=TypeConverters.toInt)

    def __init__(self, variables: Any = None, **kwargs):
        super().__init__(**kwargs)
        self._variables = variables
        self._rebuild_apply_fn()

    def _rebuild_apply_fn(self) -> None:
        from .resnet import build_resnet
        model = build_resnet(self.getModelName())
        headless = self.getCutOutputLayers() >= 1

        def apply_fn(variables, batch):
            return model.apply(variables, batch, train=False,
                               features_only=headless)

        self._apply_fn = apply_fn
        self._jitted = None


class CNTKModel(DNNModel):
    """Evaluates serialized CNTK-v2 ``.model`` graphs on TPU (reference
    cntk/CNTKModel.scala, expected path, UNVERIFIED — SURVEY.md §2.2).

    ``setModelLocation(path)`` parses the CNTK-v2 protobuf Dictionary
    (``dnn.cntk_format``), compiles the primitive-function graph to a
    jitted jax program, and streams minibatches through it — including
    the reference's *layer surgery*: ``setOutputNodeName`` cuts the graph
    at any named intermediate node (the reference's
    setOutputNode/setOutputNodeIndex contract) so a classifier ships as
    a featurizer.  Converted torch/flax weights remain loadable via
    :class:`ResNetFeaturizerModel` / :class:`mmlspark_tpu.onnx.ONNXModel`;
    this class handles the native CNTK format itself.
    """

    modelLocation = Param("modelLocation",
                          "Path to a CNTK-v2 .model file", default="",
                          typeConverter=TypeConverters.toString)
    outputNodeName = Param(
        "outputNodeName",
        "Evaluate up to this node (name or uid) instead of the graph "
        "root — CNTKModel layer surgery (empty = root)", default="",
        typeConverter=TypeConverters.toString)

    def __init__(self, apply_fn=None, variables=None, **kwargs):
        super().__init__(apply_fn=apply_fn, variables=variables, **kwargs)
        self._model_dict = None
        loc = kwargs.get("modelLocation")
        if loc:
            self._load_cntk(loc)

    def setModelLocation(self, path: str) -> "CNTKModel":
        self.setParams(modelLocation=path)
        self._load_cntk(path)
        return self

    def setOutputNodeName(self, name: str) -> "CNTKModel":
        self.setParams(outputNodeName=name)
        if self._model_dict is not None:
            self._rebuild_from_dict()
        return self

    def _load_cntk(self, path: str) -> None:
        from .cntk_format import load_model_dict
        self._model_dict = load_model_dict(path)
        self._rebuild_from_dict()

    def _rebuild_from_dict(self) -> None:
        from .cntk_format import build_eval
        out = self.getOrDefault("outputNodeName")
        apply_fn, params = build_eval(self._model_dict, out or None)
        self.setModel(apply_fn, params)

    def _load_extra(self, path: str) -> None:
        self._load_dir = path
        super()._load_extra(path)

    # persistence: embed the .model BYTES so the saved stage is
    # self-contained — a load on another machine must not depend on the
    # original modelLocation path still existing
    def _save_extra(self, path: str) -> None:
        super()._save_extra(path)
        loc = self.getOrDefault("modelLocation")
        if self._model_dict is not None:
            from .cntk_format import save_model_dict
            save_model_dict(os.path.join(path, "model.cntk"),
                            self._model_dict)
        elif loc and os.path.exists(loc):
            import shutil
            shutil.copyfile(loc, os.path.join(path, "model.cntk"))

    def _rebuild_apply_fn(self) -> None:
        emb = None
        if getattr(self, "_load_dir", None):
            emb = os.path.join(self._load_dir, "model.cntk")
        if emb and os.path.exists(emb):
            self._load_cntk(emb)
            return
        loc = self.getOrDefault("modelLocation")
        if loc and os.path.exists(loc):
            self._load_cntk(loc)
