"""ResNet family in flax, with torch state-dict import.

TPU-native replacement for the reference's pretrained CNTK ResNet models
(image/ImageFeaturizer.scala + downloader ModelSchema, expected paths,
UNVERIFIED; SURVEY.md §3.3).  The reference broadcasts a serialized CNTK
graph and evals it over JNI; here the model is a flax module jitted by XLA,
and "model surgery" (``cutOutputLayers``) maps to selecting the pooled
feature head instead of the classifier logits.

Weights: ``load_torch_state_dict`` converts a torchvision-layout ResNet
checkpoint (``conv1.weight``, ``layer1.0.conv2.weight``, …) to the flax
parameter tree, so any locally available torch checkpoint powers the
featurizer without a JVM or CNTK.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), (self.strides, self.strides),
                    padding=[(1, 1), (1, 1)], use_bias=False, name="conv1")(x)
        y = nn.BatchNorm(use_running_average=not train, name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)],
                    use_bias=False, name="conv2")(y)
        y = nn.BatchNorm(use_running_average=not train, name="bn2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               name="downsample_conv")(x)
            residual = nn.BatchNorm(use_running_average=not train,
                                    name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, name="conv1")(x)
        y = nn.BatchNorm(use_running_average=not train, name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), (self.strides, self.strides),
                    padding=[(1, 1), (1, 1)], use_bias=False, name="conv2")(y)
        y = nn.BatchNorm(use_running_average=not train, name="bn2")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, name="conv3")(y)
        y = nn.BatchNorm(use_running_average=not train, name="bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               name="downsample_conv")(x)
            residual = nn.BatchNorm(use_running_average=not train,
                                    name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """NHWC ResNet.  ``num_classes=0`` → headless (pooled features)."""

    stage_sizes: Sequence[int]
    block: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64

    @nn.compact
    def __call__(self, x, train: bool = False,
                 features_only: bool = False):
        x = nn.Conv(self.num_filters, (7, 7), (2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False, name="conv1")(x)
        x = nn.BatchNorm(use_running_average=not train, name="bn1")(x)
        x = nn.relu(x)
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)),
                    constant_values=-jnp.inf)
        x = nn.max_pool(x, (3, 3), (2, 2))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block(self.num_filters * 2 ** i, strides,
                               name=f"layer{i + 1}_{j}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))        # global average pool
        if features_only or self.num_classes == 0:
            return x
        return nn.Dense(self.num_classes, name="fc")(x)


_CONFIGS = {
    "resnet18": ([2, 2, 2, 2], BasicBlock),
    "resnet34": ([3, 4, 6, 3], BasicBlock),
    "resnet50": ([3, 4, 6, 3], Bottleneck),
    "resnet101": ([3, 4, 23, 3], Bottleneck),
    "resnet152": ([3, 8, 36, 3], Bottleneck),
}


def build_resnet(name: str = "resnet50", num_classes: int = 1000) -> ResNet:
    if name not in _CONFIGS:
        raise ValueError(f"Unknown ResNet {name!r}; have {sorted(_CONFIGS)}")
    sizes, block = _CONFIGS[name]
    return ResNet(stage_sizes=sizes, block=block, num_classes=num_classes)


def init_params(model: ResNet, image_size: int = 224, seed: int = 0):
    x = jnp.zeros((1, image_size, image_size, 3))
    return model.init(jax.random.PRNGKey(seed), x)


# -- torch state-dict conversion ---------------------------------------------

def load_torch_state_dict(model: ResNet, state_dict: Dict[str, Any]):
    """Convert a torchvision-layout ResNet state dict to flax variables."""
    params: Dict[str, Any] = {}
    batch_stats: Dict[str, Any] = {}

    def a(t):
        return np.asarray(t, dtype=np.float32)

    def conv_w(t):
        return np.transpose(a(t), (2, 3, 1, 0))  # OIHW -> HWIO

    def put(tree, path, val):
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = jnp.asarray(val)

    def bn(prefix_torch, path_flax):
        put(params, path_flax + ("scale",), a(state_dict[prefix_torch + ".weight"]))
        put(params, path_flax + ("bias",), a(state_dict[prefix_torch + ".bias"]))
        put(batch_stats, path_flax + ("mean",),
            a(state_dict[prefix_torch + ".running_mean"]))
        put(batch_stats, path_flax + ("var",),
            a(state_dict[prefix_torch + ".running_var"]))

    put(params, ("conv1", "kernel"), conv_w(state_dict["conv1.weight"]))
    bn("bn1", ("bn1",))
    for i, n_blocks in enumerate(model.stage_sizes):
        for j in range(n_blocks):
            tp = f"layer{i + 1}.{j}"
            fp = f"layer{i + 1}_{j}"
            convs = ["conv1", "conv2"] + (
                ["conv3"] if model.block is Bottleneck else [])
            for c in convs:
                put(params, (fp, c, "kernel"),
                    conv_w(state_dict[f"{tp}.{c}.weight"]))
                bn(f"{tp}.bn{c[-1]}", (fp, f"bn{c[-1]}"))
            if f"{tp}.downsample.0.weight" in state_dict:
                put(params, (fp, "downsample_conv", "kernel"),
                    conv_w(state_dict[f"{tp}.downsample.0.weight"]))
                bn(f"{tp}.downsample.1", (fp, "downsample_bn"))
    if model.num_classes and "fc.weight" in state_dict:
        put(params, ("fc", "kernel"), a(state_dict["fc.weight"]).T)
        put(params, ("fc", "bias"), a(state_dict["fc.bias"]))
    return {"params": params, "batch_stats": batch_stats}
