"""ImageFeaturizer: headless-DNN image featurization.

TPU-native re-implementation of the reference's flagship inference pipeline
(image/ImageFeaturizer.scala, expected path, UNVERIFIED; SURVEY.md §3.3):
``ImageTransformer`` (resize/crop) → ``UnrollImage`` → headless ``CNTKModel``
becomes resize/normalize (batched jax ops) → jitted flax ResNet forward with
the classifier head cut.  One XLA program per minibatch instead of per-row
JNI; this is the BASELINE.md "ImageFeaturizer ResNet-50 imgs/sec/chip"
config.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..core.params import Param, TypeConverters, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.schema import DataTable
from ..dnn.model import ResNetFeaturizerModel
from ..dnn.resnet import build_resnet, init_params
from .transformer import ImageTransformer

# torchvision ImageNet normalization, in 0-255 space
_IMAGENET_MEAN = [123.675, 116.28, 103.53]
_IMAGENET_STD = [58.395, 57.12, 57.375]


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    """resize → normalize → headless ResNet forward, all on device."""

    modelName = Param("modelName", "DNN to featurize with",
                      default="resnet50", typeConverter=TypeConverters.toString)
    cutOutputLayers = Param("cutOutputLayers",
                            "Layers to cut from the head: 1 -> pooled "
                            "features, 0 -> logits", default=1,
                            typeConverter=TypeConverters.toInt)
    imageHeight = Param("imageHeight", "Input height", default=224,
                        typeConverter=TypeConverters.toInt)
    imageWidth = Param("imageWidth", "Input width", default=224,
                       typeConverter=TypeConverters.toInt)
    miniBatchSize = Param("miniBatchSize", "Rows per device minibatch",
                          default=64, typeConverter=TypeConverters.toInt)
    channelsBGR = Param("channelsBGR",
                        "Input images are BGR (OpenCV order) and will be "
                        "converted to RGB", default=False,
                        typeConverter=TypeConverters.toBool)
    cntkModelLocation = Param(
        "cntkModelLocation",
        "Featurize through a native CNTK-v2 .model graph instead of the "
        "flax ResNet — the reference's own ImageFeaturizer architecture "
        "(ImageTransformer -> UnrollImage -> headless CNTKModel)",
        default="", typeConverter=TypeConverters.toString)
    cntkOutputNodeName = Param(
        "cntkOutputNodeName",
        "Layer-surgery cut point in the CNTK graph (empty = root)",
        default="", typeConverter=TypeConverters.toString)

    def __init__(self, variables: Any = None, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)
        self._variables = variables

    # -- weights -------------------------------------------------------------

    def setWeights(self, variables: Any) -> "ImageFeaturizer":
        self._variables = variables
        return self

    def loadTorchCheckpoint(self, path: str) -> "ImageFeaturizer":
        """Load a torchvision-layout ResNet checkpoint (.pt/.pth)."""
        import torch
        from ..dnn.resnet import load_torch_state_dict
        sd = torch.load(path, map_location="cpu", weights_only=True)
        if hasattr(sd, "state_dict"):
            sd = sd.state_dict()
        model = build_resnet(self.getModelName())
        self._variables = load_torch_state_dict(model, sd)
        return self

    def _ensure_variables(self):
        if self._variables is None:
            from ..downloader import ModelDownloader
            path = ModelDownloader().find_local_checkpoint(
                self.getModelName())
            if path is not None:
                self.loadTorchCheckpoint(path)
            else:
                import logging
                logging.getLogger("mmlspark_tpu").warning(
                    "ImageFeaturizer: no checkpoint for %s found; using "
                    "RANDOM weights (features are untrained). Provide one "
                    "via loadTorchCheckpoint()/setWeights().",
                    self.getModelName())
                self._variables = init_params(
                    build_resnet(self.getModelName()),
                    self.getImageHeight())
        return self._variables

    # -- execution -----------------------------------------------------------

    def _transform(self, table: DataTable) -> DataTable:
        prep = ImageTransformer(inputCol=self.getInputCol(),
                                outputCol="__prepped__")
        prep.resize(self.getImageHeight(), self.getImageWidth())
        if self.getChannelsBGR():
            prep.colorFormat("rgb")
        prep.normalize(_IMAGENET_MEAN, _IMAGENET_STD)
        prepped = prep._transform(table)

        cntk_loc = self.getOrDefault("cntkModelLocation")
        if cntk_loc:
            # the reference's pipeline shape: headless CNTK graph eval.
            # Our CNTK conv convention is (C, H, W); ImageTransformer
            # emits (H, W, C) — transpose per row, flatten the surgery
            # output to the flat feature vector UnrollImage would emit.
            from ..dnn.model import CNTKModel
            col = prepped["__prepped__"]
            chw = np.stack([np.asarray(v, np.float32).transpose(2, 0, 1)
                            for v in col])
            dnn = CNTKModel(inputCol="__chw__",
                            outputCol=self.getOutputCol(),
                            miniBatchSize=self.getMiniBatchSize())
            node = self.getOrDefault("cntkOutputNodeName")
            if node:
                dnn.setParams(outputNodeName=node)
            dnn.setModelLocation(cntk_loc)
            out = dnn._transform(
                prepped.withColumn("__chw__", chw))
            feats = np.asarray(out[self.getOutputCol()])
            if feats.ndim > 2:
                out = out.withColumn(self.getOutputCol(),
                                     feats.reshape(len(feats), -1))
            return out.drop("__prepped__", "__chw__")

        dnn = ResNetFeaturizerModel(
            variables=self._ensure_variables(),
            inputCol="__prepped__", outputCol=self.getOutputCol(),
            modelName=self.getModelName(),
            cutOutputLayers=self.getCutOutputLayers(),
            miniBatchSize=self.getMiniBatchSize())
        out = dnn._transform(prepped)
        return out.drop("__prepped__")

    def _save_extra(self, path: str) -> None:
        import jax, os, pickle
        with open(os.path.join(path, "variables.pkl"), "wb") as f:
            pickle.dump(jax.device_get(self._ensure_variables()), f)

    def _load_extra(self, path: str) -> None:
        import os, pickle
        with open(os.path.join(path, "variables.pkl"), "rb") as f:
            self._variables = pickle.load(f)
