from .transformer import ImageTransformer, UnrollImage, ImageSetAugmenter
from .featurizer import ImageFeaturizer

__all__ = ["ImageTransformer", "UnrollImage", "ImageSetAugmenter",
           "ImageFeaturizer"]
