from .transformer import (ImageTransformer, UnrollBinaryImage, UnrollImage,
                          ImageSetAugmenter)
from .featurizer import ImageFeaturizer

__all__ = ["ImageTransformer", "UnrollBinaryImage", "UnrollImage",
           "ImageSetAugmenter",
           "ImageFeaturizer"]
