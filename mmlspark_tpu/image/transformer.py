"""ImageTransformer / UnrollImage / ImageSetAugmenter.

TPU-native re-implementation of the reference's image pipeline stages
(opencv/ImageTransformer.scala, image/UnrollImage.scala,
image/ImageSetAugmenter.scala — expected paths, UNVERIFIED; SURVEY.md §2.1).
The reference exposes an OpenCV-stage DSL (``.resize(h, w).crop(...)``)
executed per row over JNI; here the same DSL builds a list of batched tensor
ops (ops/image.py) executed as ONE jitted program per image-shape group:

* ragged input images are grouped by (H, W, C) so each distinct shape
  compiles once and runs batched;
* after a ``resize`` stage shapes are uniform, so downstream stages fuse
  into the same XLA program — the TPU answer to per-row JNI calls.

Image columns are object columns of HWC uint8/float arrays, or a uniform
``(N, H, W, C)`` numeric array.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import Param, TypeConverters, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.schema import DataTable
from ..core import serialize
from ..ops import image as imops


def _to_batches(col: np.ndarray) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Group an image column into (row_indices, NHWC float32 batch) groups."""
    if isinstance(col, np.ndarray) and col.ndim == 4:
        return [(np.arange(col.shape[0]), np.asarray(col, np.float32))]
    groups: Dict[Tuple[int, ...], List[int]] = {}
    imgs = []
    for i, im in enumerate(col):
        im = np.asarray(im)
        if im.ndim == 2:
            im = im[:, :, None]
        imgs.append(im)
        groups.setdefault(im.shape, []).append(i)
    return [(np.asarray(idx),
             np.stack([imgs[i] for i in idx]).astype(np.float32))
            for idx in (np.asarray(v) for v in groups.values())]


def _apply_stages(batch: jnp.ndarray, stages: List[Dict[str, Any]]
                  ) -> jnp.ndarray:
    for st in stages:
        op = st["op"]
        if op == "resize":
            batch = imops.resize(batch, st["height"], st["width"])
        elif op == "centerCrop":
            batch = imops.center_crop(batch, st["height"], st["width"])
        elif op == "crop":
            batch = imops.crop(batch, st["y"], st["x"], st["height"],
                               st["width"])
        elif op == "colorFormat":
            fmt = st["format"]
            if fmt in ("gray", "grayscale"):
                batch = imops.to_grayscale(batch)
            elif fmt in ("rgb", "bgr"):  # swap channel order
                batch = imops.bgr_to_rgb(batch)
            else:
                raise ValueError(f"Unknown color format {fmt!r}")
        elif op == "flip":
            batch = imops.flip(batch, horizontal=st.get("horizontal", True))
        elif op == "blur":
            batch = imops.gaussian_blur(batch, size=int(st.get("size", 3)),
                                        sigma=float(st.get("sigma", 0.0)))
        elif op == "threshold":
            batch = imops.threshold(batch, st["threshold"],
                                    st.get("maxVal", 255.0),
                                    st.get("kind", "binary"))
        elif op == "normalize":
            batch = imops.normalize(batch, st["mean"], st["std"],
                                    st.get("scale", 1.0))
        else:
            raise ValueError(f"Unknown image stage {op!r}")
    return batch


@functools.lru_cache(maxsize=64)
def _compiled_pipeline(stages_json: str):
    """One jitted program per distinct stage list (shared across calls)."""
    stages = json.loads(stages_json)
    return jax.jit(lambda b: _apply_stages(b, stages))


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """OpenCV-style stage DSL compiled to batched jitted tensor ops."""

    stages = Param("stages", "Ordered list of image op descriptors",
                   default=None, typeConverter=TypeConverters.toList)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        super().__init__(**kwargs)
        if self.getStages() is None:
            self.setStages([])

    # -- DSL (mirrors the reference's ImageTransformer builder API) ---------

    def _add(self, **st) -> "ImageTransformer":
        self.setStages(list(self.getStages()) + [st])
        return self

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="resize", height=int(height), width=int(width))

    def centerCrop(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="centerCrop", height=int(height),
                         width=int(width))

    def crop(self, x: int, y: int, height: int, width: int):
        return self._add(op="crop", x=int(x), y=int(y), height=int(height),
                         width=int(width))

    def colorFormat(self, fmt: str) -> "ImageTransformer":
        return self._add(op="colorFormat", format=fmt)

    def flip(self, horizontal: bool = True) -> "ImageTransformer":
        return self._add(op="flip", horizontal=bool(horizontal))

    def blur(self, size: int = 3, sigma: float = 0.0) -> "ImageTransformer":
        return self._add(op="blur", size=int(size), sigma=float(sigma))

    def threshold(self, threshold: float, maxVal: float = 255.0,
                  kind: str = "binary") -> "ImageTransformer":
        return self._add(op="threshold", threshold=float(threshold),
                         maxVal=float(maxVal), kind=kind)

    def normalize(self, mean, std, scale: float = 1.0):
        return self._add(op="normalize", mean=list(mean), std=list(std),
                         scale=float(scale))

    # -- execution -----------------------------------------------------------

    def _transform(self, table: DataTable) -> DataTable:
        col = table[self.getInputCol()]
        stages = self.getStages()
        batches = _to_batches(col)
        fn = _compiled_pipeline(json.dumps(stages))
        n = len(table)
        outs: Optional[np.ndarray] = None
        results = []
        for idx, batch in batches:
            out = np.asarray(fn(jnp.asarray(batch)))
            results.append((idx, out))
        shapes = {r.shape[1:] for _, r in results}
        if len(shapes) == 1:
            shape = shapes.pop()
            outs = np.empty((n,) + shape, np.float32)
            for idx, r in results:
                outs[idx] = r
        else:  # still ragged: object column
            outs = np.empty(n, object)
            for idx, r in results:
                for i, row in zip(idx, r):
                    outs[i] = row
        return table.withColumn(self.getOutputCol(), outs)


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """HWC image column → flat numeric vector column (reference UnrollImage)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "unrolled")
        super().__init__(**kwargs)

    def _transform(self, table: DataTable) -> DataTable:
        col = table[self.getInputCol()]
        if isinstance(col, np.ndarray) and col.ndim == 4:
            flat = col.reshape(col.shape[0], -1).astype(np.float64)
        else:
            rows = [np.asarray(im, np.float64).reshape(-1) for im in col]
            widths = {len(r) for r in rows}
            if len(widths) != 1:
                raise ValueError(
                    "UnrollImage requires uniformly-sized images; add an "
                    "ImageTransformer().resize(...) stage first")
            flat = np.stack(rows)
        return table.withColumn(self.getOutputCol(), flat)


class UnrollBinaryImage(Transformer, HasInputCol, HasOutputCol):
    """Encoded image BYTES column (PNG/JPEG/BMP) → flat numeric vector
    column (reference image/UnrollImage.scala UnrollBinaryImage, expected
    path, UNVERIFIED): decode + optional resize + unroll in one stage, for
    tables straight out of the binary datasource."""

    width = Param("width", "Resize width before unrolling (0 keeps size)",
                  default=0, typeConverter=TypeConverters.toInt)
    height = Param("height", "Resize height before unrolling (0 keeps size)",
                   default=0, typeConverter=TypeConverters.toInt)
    channelsBGR = Param(
        "channelsBGR",
        "Unroll in BGR channel order (the reference decodes via OpenCV/"
        "ImageSchema, which is BGR — keep True for vectors interchangeable "
        "with reference-produced ones; False gives PIL-native RGB)",
        default=True, typeConverter=TypeConverters.toBool)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "bytes")
        kwargs.setdefault("outputCol", "unrolled")
        super().__init__(**kwargs)

    def _transform(self, table: DataTable) -> DataTable:
        import io as _io

        from PIL import Image

        w, h = self.getWidth(), self.getHeight()
        if (w > 0) != (h > 0):
            raise ValueError(
                "UnrollBinaryImage: set BOTH width and height to resize "
                f"(got width={w}, height={h})")
        bgr = self.getChannelsBGR()
        rows = []
        for blob in table[self.getInputCol()]:
            img = Image.open(_io.BytesIO(bytes(blob))).convert("RGB")
            if w > 0 and h > 0:
                img = img.resize((w, h))
            arr = np.asarray(img, np.float64)
            if bgr:
                arr = arr[:, :, ::-1]
            rows.append(arr.reshape(-1))
        widths = {len(r) for r in rows}
        if len(widths) > 1:
            raise ValueError(
                "UnrollBinaryImage requires uniformly-sized images; set "
                "width/height to resize while decoding")
        flat = np.stack(rows) if rows else np.zeros((0, 0))
        return table.withColumn(self.getOutputCol(), flat)


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Dataset augmentation by flips: emits 2x (or 4x) rows per input
    (reference image/ImageSetAugmenter.scala, expected path, UNVERIFIED)."""

    flipLeftRight = Param("flipLeftRight", "Emit horizontally flipped copies",
                          default=True, typeConverter=TypeConverters.toBool)
    flipUpDown = Param("flipUpDown", "Emit vertically flipped copies",
                       default=False, typeConverter=TypeConverters.toBool)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        super().__init__(**kwargs)

    def _transform(self, table: DataTable) -> DataTable:
        col = table[self.getInputCol()]
        tables = [table.withColumn(self.getOutputCol(), col)]
        def flipped(axis):
            if isinstance(col, np.ndarray) and col.ndim == 4:
                return np.flip(col, axis=axis)
            out = np.empty(len(col), object)
            for i, im in enumerate(col):
                out[i] = np.flip(np.asarray(im), axis=axis - 1)
            return out
        if self.getFlipLeftRight():
            tables.append(table.withColumn(self.getOutputCol(), flipped(2)))
        if self.getFlipUpDown():
            tables.append(table.withColumn(self.getOutputCol(), flipped(1)))
        out = tables[0]
        for t in tables[1:]:
            out = out.concat(t)
        return out
