"""VW-style feature hashing into a fixed 2^numBits dense vector.

Reference: vw/VowpalWabbitFeaturizer.scala, vw/VowpalWabbitInteractions.scala
(expected paths, UNVERIFIED — SURVEY.md §2.1).

The reference emits sparse VW example strings; a TPU wants dense,
statically-shaped operands, so here hashing scatters into a dense
``(rows, 2^numBits)`` float column (numBits defaults low enough that dense
is cheap; raise it for genuinely sparse workloads and the matmul against a
weight vector still maps to the MXU).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..core.params import HasInputCols, HasOutputCol, Param, TypeConverters
from ..core.pipeline import Transformer
from ..core.schema import DataTable
from ..featurize.hashing import murmur3_32


def _hash(s: str, seed: int) -> int:
    return murmur3_32(s.encode("utf-8"), seed)


class VowpalWabbitFeaturizer(HasInputCols, HasOutputCol, Transformer):
    """Hashes mixed-type columns into one dense vector column.

    Per-column behavior (mirrors the reference featurizer):

    * numeric scalar → weight at ``hash(colName)``
    * string → weight 1.0 at ``hash(colName + "=" + value)``
    * numeric vector → element i at ``hash(colName + "_" + i)``
    * list of strings → weight 1.0 per token at ``hash(colName + "=" + tok)``
    """

    outputCol = Param("outputCol", "Output vector column", default="features",
                      typeConverter=TypeConverters.toString)
    numBits = Param("numBits", "log2 of the hash space", default=12,
                    typeConverter=TypeConverters.toInt,
                    validator=lambda v: 1 <= v <= 24)
    sumCollisions = Param("sumCollisions",
                          "Sum colliding values (else last write wins)",
                          default=True, typeConverter=TypeConverters.toBool)
    seed = Param("seed", "Murmur seed", default=0,
                 typeConverter=TypeConverters.toInt)

    def _transform(self, table: DataTable) -> DataTable:
        dim = 1 << self.getNumBits()
        mask = dim - 1
        seed = self.getSeed()
        n = len(table)
        out = np.zeros((n, dim), dtype=np.float32)
        summing = self.getSumCollisions()
        for name in self.getInputCols():
            col = table[name]
            if col.ndim == 2:
                idx = np.asarray(
                    [_hash(f"{name}_{i}", seed) & mask
                     for i in range(col.shape[1])], dtype=np.int64)
                vals = col.astype(np.float32)
                for j, slot in enumerate(idx):
                    if summing:
                        out[:, slot] += vals[:, j]
                    else:
                        out[:, slot] = vals[:, j]
            elif col.dtype.kind in "fiub":
                slot = _hash(name, seed) & mask
                if summing:
                    out[:, slot] += col.astype(np.float32)
                else:
                    out[:, slot] = col.astype(np.float32)
            else:
                for r, v in enumerate(col):
                    tokens = v if isinstance(v, (list, tuple)) else [v]
                    for tok in tokens:
                        slot = _hash(f"{name}={tok}", seed) & mask
                        if summing:
                            out[r, slot] += 1.0
                        else:
                            out[r, slot] = 1.0
        return table.withColumn(self.getOutputCol(), out)


class VowpalWabbitInteractions(HasInputCols, HasOutputCol, Transformer):
    """Quadratic namespace crosses: the outer product of the input vector
    columns, re-hashed into the output space (vw/VowpalWabbitInteractions
    .scala — VW's ``-q ab`` flag)."""

    outputCol = Param("outputCol", "Output vector column",
                      default="interactions",
                      typeConverter=TypeConverters.toString)
    numBits = Param("numBits", "log2 of the hash space", default=12,
                    typeConverter=TypeConverters.toInt,
                    validator=lambda v: 1 <= v <= 24)

    def _transform(self, table: DataTable) -> DataTable:
        cols = [np.asarray(table[c], dtype=np.float32)
                for c in self.getInputCols()]
        for c, name in zip(cols, self.getInputCols()):
            if c.ndim != 2:
                raise ValueError(
                    f"Interactions need vector columns; {name!r} has shape "
                    f"{c.shape} — run VowpalWabbitFeaturizer first")
        dim = 1 << self.getNumBits()
        n = len(table)
        if len(cols) < 2:
            raise ValueError("Need at least two input vector columns")
        # pairwise crosses of nonzero slots, rehashed by slot-index pair
        out = np.zeros((n, dim), dtype=np.float32)
        for a_i in range(len(cols)):
            for b_i in range(a_i + 1, len(cols)):
                a, b = cols[a_i], cols[b_i]
                # slot pair (i, j) → slot (i * P + j) mod dim; P a big prime
                # mirrors VW's hash-combine of namespace feature hashes
                ii, jj = np.nonzero(a)[1], np.nonzero(b)[1]
                slots_a = np.unique(ii)
                slots_b = np.unique(jj)
                for i in slots_a:
                    combined = (i.astype(np.int64) * 16777619 +
                                slots_b.astype(np.int64)) % dim
                    # np.add.at: colliding combined slots must SUM, and
                    # fancy-index += silently drops duplicate contributions
                    np.add.at(out, (slice(None), combined),
                              a[:, [i]] * b[:, slots_b])
        return table.withColumn(self.getOutputCol(), out)
