"""VW-equivalent linear learners: AdaGrad SGD under ``lax.scan``.

Reference: vw/VowpalWabbitBase.scala, vw/VowpalWabbitClassifier.scala,
vw/VowpalWabbitRegressor.scala (expected paths, UNVERIFIED — SURVEY.md
§2.1).  The reference drives the C++ VW engine per-executor and averages
models (spanning-tree allreduce); here the whole pass is jit'd jax:

* minibatches scanned with ``lax.scan`` (static shapes, one compile)
* adaptive per-coordinate learning rate ``lr / (sqrt(G) + eps)`` with
  ``G`` the AdaGrad accumulator — VW's ``--adaptive`` default
* ``powerT`` decay on the pass-level rate (VW's default 0.5)
* distributed: per-shard scan + parameter mean over the mesh data axis
  (``shard_map`` + ``psum``), the model-averaging strategy of the
  reference (SURVEY.md §2.3)
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import (HasFeaturesCol, HasLabelCol, HasPredictionCol,
                           HasProbabilityCol, HasRawPredictionCol,
                           HasWeightCol, Param, TypeConverters)
from ..core.pipeline import Estimator, Model
from ..core.schema import DataTable, features_matrix
from ..core import serialize


class _VWParams(HasFeaturesCol, HasLabelCol, HasWeightCol, HasPredictionCol):
    numPasses = Param("numPasses", "Passes over the data", default=1,
                      typeConverter=TypeConverters.toInt)
    learningRate = Param("learningRate", "Base learning rate", default=0.5,
                         typeConverter=TypeConverters.toFloat)
    powerT = Param("powerT", "t^-powerT rate decay across passes",
                   default=0.5, typeConverter=TypeConverters.toFloat)
    l1 = Param("l1", "L1 regularization (lazy proximal)", default=0.0,
               typeConverter=TypeConverters.toFloat)
    l2 = Param("l2", "L2 regularization", default=0.0,
               typeConverter=TypeConverters.toFloat)
    batchSize = Param("batchSize", "Minibatch rows per SGD step", default=256,
                      typeConverter=TypeConverters.toInt)
    hashSeed = Param("hashSeed", "Seed for shuffling", default=42,
                     typeConverter=TypeConverters.toInt)


@partial(jax.jit, static_argnames=("loss", "batch", "passes"))
def _train_sgd(X, y, sw, w0, b0, lr, power_t, l2, loss: str, batch: int,
               passes: int):
    """AdaGrad SGD over minibatches; returns (w, b).

    Callers pad rows to a batch multiple (wrap-around), so every example
    contributes.  ``sw`` is the per-row sample weight.
    """
    n, d = X.shape
    n_batches = n // batch

    def one_pass(carry, pass_i):
        w, b, gw, gb = carry
        decay = (pass_i + 1.0) ** (-power_t)

        def step(carry, i):
            w, b, gw, gb = carry
            sl = jax.lax.dynamic_slice_in_dim(X, i * batch, batch)
            yl = jax.lax.dynamic_slice_in_dim(y, i * batch, batch)
            wl = jax.lax.dynamic_slice_in_dim(sw, i * batch, batch)
            margin = sl @ w + b
            if loss == "logistic":
                p = jax.nn.sigmoid(margin)
                grad_m = p - yl
            else:  # squared
                grad_m = margin - yl
            grad_m = grad_m * wl
            denom = jnp.maximum(jnp.sum(wl), 1e-12)
            g_w = sl.T @ grad_m / denom + l2 * w
            g_b = jnp.sum(grad_m) / denom
            gw = gw + g_w * g_w
            gb = gb + g_b * g_b
            w = w - lr * decay * g_w / (jnp.sqrt(gw) + 1e-6)
            b = b - lr * decay * g_b / (jnp.sqrt(gb) + 1e-6)
            return (w, b, gw, gb), None

        (w, b, gw, gb), _ = jax.lax.scan(
            step, (w, b, gw, gb), jnp.arange(n_batches))
        return (w, b, gw, gb), None

    gw0 = jnp.zeros_like(w0)
    gb0 = jnp.zeros_like(b0)
    (w, b, _, _), _ = jax.lax.scan(
        one_pass, (w0, b0, gw0, gb0), jnp.arange(passes))
    return w, b


@jax.jit
def _linear_margin(X, w, b):
    return X @ w + b


class _VWBase(_VWParams, Estimator):
    __abstractstage__ = True
    _loss = "squared"

    def _fit(self, table: DataTable):
        X = np.asarray(features_matrix(table, self.getFeaturesCol()),
                       dtype=np.float32)
        y = np.asarray(table[self.getLabelCol()], dtype=np.float32)
        if self._loss == "logistic":
            # accept {-1,1} or {0,1}
            y = np.where(y > 0, 1.0, 0.0).astype(np.float32)
        n, d = X.shape
        weight_col = self.getWeightCol()
        sw = (np.asarray(table[weight_col], dtype=np.float32)
              if weight_col and weight_col in table
              else np.ones(n, dtype=np.float32))
        rng = np.random.default_rng(self.getHashSeed())
        perm = rng.permutation(n)
        batch = min(self.getBatchSize(), n)
        # pad to a batch multiple by wrapping, so the ragged tail trains too
        n_padded = ((n + batch - 1) // batch) * batch
        idx = perm[np.arange(n_padded) % n]
        X, y, sw = X[idx], y[idx], sw[idx]
        w, b = _train_sgd(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(sw),
            jnp.zeros(d, jnp.float32), jnp.asarray(0.0, jnp.float32),
            jnp.asarray(self.getLearningRate(), jnp.float32),
            jnp.asarray(self.getPowerT(), jnp.float32),
            jnp.asarray(self.getL2(), jnp.float32),
            self._loss, int(batch), int(self.getNumPasses()))
        # lazy L1: soft-threshold once after training (proximal step)
        l1 = self.getL1()
        w = np.asarray(w)
        if l1 > 0:
            w = np.sign(w) * np.maximum(np.abs(w) - l1, 0.0)
        model = self._model_cls(weights=w, intercept=float(b))
        model.setParams(**{k: v for k, v in self._iterSetParams()
                           if model.hasParam(k)})
        return model


class _VWModelBase(_VWParams, Model):
    __abstractstage__ = True

    def __init__(self, weights: Optional[np.ndarray] = None,
                 intercept: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self._w = None if weights is None else np.asarray(weights,
                                                          dtype=np.float32)
        self._b = float(intercept)

    @property
    def weights(self) -> np.ndarray:
        return self._w.copy()

    @property
    def intercept(self) -> float:
        return self._b

    def _margin(self, table: DataTable) -> np.ndarray:
        X = np.asarray(features_matrix(table, self.getFeaturesCol()),
                       dtype=np.float32)
        return np.asarray(_linear_margin(
            jnp.asarray(X), jnp.asarray(self._w), jnp.asarray(self._b)))

    def _save_extra(self, path: str) -> None:
        serialize.save_arrays(path, weights=self._w,
                              intercept=np.asarray([self._b]))

    def _load_extra(self, path: str) -> None:
        arrays = serialize.load_arrays(path)
        self._w = arrays["weights"]
        self._b = float(arrays["intercept"][0])


class VowpalWabbitClassificationModel(_VWModelBase, HasProbabilityCol,
                                      HasRawPredictionCol):
    def _transform(self, table: DataTable) -> DataTable:
        margin = self._margin(table)
        p1 = 1.0 / (1.0 + np.exp(-margin))
        prob = np.stack([1.0 - p1, p1], axis=1)
        return table.withColumns({
            self.getRawPredictionCol(): np.stack([-margin, margin], axis=1),
            self.getProbabilityCol(): prob,
            self.getPredictionCol(): (p1 > 0.5).astype(np.float64),
        })


class VowpalWabbitRegressionModel(_VWModelBase):
    def _transform(self, table: DataTable) -> DataTable:
        return table.withColumn(self.getPredictionCol(),
                                self._margin(table).astype(np.float64))


class VowpalWabbitClassifier(_VWBase):
    """Online logistic learner (vw/VowpalWabbitClassifier.scala)."""
    _loss = "logistic"
    _model_cls = VowpalWabbitClassificationModel


class VowpalWabbitRegressor(_VWBase):
    """Online squared-loss learner (vw/VowpalWabbitRegressor.scala)."""
    _loss = "squared"
    _model_cls = VowpalWabbitRegressionModel
