"""Vowpal-Wabbit-equivalent online linear learning (reference ``vw/``).

Reference: src/main/scala/com/microsoft/ml/spark/vw/ (expected paths,
UNVERIFIED — SURVEY.md §2.1): VowpalWabbitClassifier/Regressor (JNI to the
C++ VW engine), VowpalWabbitFeaturizer (murmur feature hashing),
VowpalWabbitInteractions (namespace crosses).

TPU-native design (SURVEY.md §2.2): the VW capability actually exercised is
hashed linear/logistic SGD with adaptive (AdaGrad-style) learning rates.
Hashing runs on host (murmur3, bit-compatible with the featurize package);
the weight vector lives on device and the training pass is a single
``lax.scan`` over minibatches — each step is one (B × D) · (D,) matvec on
the MXU plus elementwise updates.  Distributed training uses model averaging
over the mesh data axis (``psum``/mean), the same strategy the reference's
VW spanning-tree allreduce implements.
"""

from .featurizer import VowpalWabbitFeaturizer, VowpalWabbitInteractions
from .learners import (
    VowpalWabbitClassificationModel,
    VowpalWabbitClassifier,
    VowpalWabbitRegressionModel,
    VowpalWabbitRegressor,
)

__all__ = [
    "VowpalWabbitFeaturizer", "VowpalWabbitInteractions",
    "VowpalWabbitClassifier", "VowpalWabbitClassificationModel",
    "VowpalWabbitRegressor", "VowpalWabbitRegressionModel",
]
