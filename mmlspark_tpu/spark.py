"""Spark interop — the deployment-shape adapter (SURVEY.md §7 step 7).

The reference IS a Spark library; this framework replaces its execution
engine but keeps the Spark deployment story available: drive
mmlspark_tpu stages from a PySpark session, with executors running the
jitted compute against their local accelerator.  Nothing here imports
pyspark at module load — every entry point degrades cleanly when Spark
is absent (the common case for pure-TPU deployments), and the
``mapInPandas``-shaped scoring closure is a plain iterator-of-pandas
contract, so the executor-side path is testable without a JVM.

Pattern::

    from mmlspark_tpu.spark import from_spark, score_udf, to_spark

    table = from_spark(spark_df)               # driver: Arrow -> columns
    model = LightGBMClassifier(...).fit(table) # TPU training
    scored = spark_df.mapInPandas(             # executors: batched score
        score_udf(model, result_cols=["probability", "prediction"]),
        schema="...")

Reference analog: the generated PySpark wrappers + JNI scoring UDFs
(codegen/PySparkWrapper.scala, lightgbm scoring UDF; expected paths,
UNVERIFIED).
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, Optional

import numpy as np


def _qid_digest(v) -> int:
    """64-bit stable digest of an original query id (the spans-shards
    cross-check compares these across hosts)."""
    import hashlib
    return int.from_bytes(
        hashlib.sha1(str(v).encode("utf-8")).digest()[:8], "big")


def spark_available() -> bool:
    try:
        import pyspark  # noqa: F401
        return True
    except ImportError:
        return False


def from_spark(df, columns: Optional[List[str]] = None):
    """PySpark DataFrame → a pandas table our estimators consume.

    Uses Arrow-backed ``toPandas`` (enable
    ``spark.sql.execution.arrow.pyspark.enabled`` for zero-copy
    collection).  ``columns`` optionally projects before collecting —
    always project: the driver materializes what you collect.
    """
    if not (hasattr(df, "toPandas") and hasattr(df, "select")):
        raise TypeError(
            f"from_spark expects a PySpark DataFrame (got {type(df)!r})")
    if columns is not None:
        df = df.select(*columns)
    return df.toPandas()


def to_spark(table, spark):
    """Pandas/dict table → PySpark DataFrame via ``createDataFrame``.

    Vector columns become plain Python lists (``tolist``): numpy cells
    break Spark's non-Arrow row-type inference."""
    import pandas as pd
    from .core.schema import to_table
    if not isinstance(table, pd.DataFrame):
        table = to_table(table).toPandas()
    table = table.copy()
    for c in table.columns:
        first = table[c].iloc[0] if len(table) else None
        if isinstance(first, np.ndarray):
            table[c] = [np.asarray(v).tolist() for v in table[c]]
    return spark.createDataFrame(table)


def executor_train_fn(mapper, params, num_tasks: int, coordinator: str,
                      objective: str = "binary",
                      feature_col: str = "features",
                      label_col: str = "label",
                      weight_col: Optional[str] = None,
                      group_col: Optional[str] = None,
                      ranking: Optional[dict] = None
                      ) -> Callable[[int, Iterable], Iterator]:
    """Executor-side TRAINING closure — the reference's deployment shape,
    where training happens INSIDE the executors (SURVEY.md §3.1), not on
    a collecting driver.

    Returns ``fn(task_index, iterator_of_pandas) ->
    Iterator[pandas.DataFrame]``, the contract of a Spark barrier task::

        mapper = fit_bin_mapper(sample_X, max_bin=...)   # driver: bin
        fn = executor_train_fn(mapper, TrainParams(...), D,
                               f"{driver_host}:{port}")  # bounds broadcast
        model_rows = (df.repartition(D).rdd.barrier()
            .mapPartitions(lambda it: fn(
                TaskContext.get().partitionId(), to_pandas_batches(it)))
            .collect())                      # task 0 emits the model text

    Each task feeds ONLY its partition's binned rows into the global
    device mesh via the None-slot sharded-ingestion path
    (``engine.train`` with ``shard_rows``): no host ever materializes
    another host's rows — the Criteo-1TB shape.  Labels/weights are 1-D
    metadata and are allgathered (the objective needs global stats).
    Rendezvous is ``jax.distributed`` over the coordinator address,
    standing in for the reference's driver-socket rendezvous
    (expected path lightgbm/LightGBMUtils.scala networkInit,
    UNVERIFIED).

    Spark-free testable: the returned fn is plain Python —
    ``tests/test_spark_adapter.py`` drives it with real separate
    processes.

    Ranking: pass ``objective="lambdarank"`` plus ``group_col`` (and,
    optionally ``ranking={"sigma": ..., "truncation_level": ...}``).
    Each partition must hold WHOLE queries (partition the DataFrame by
    the group column — the reference likewise needs group-contiguous
    partitions for distributed lambdarank); a query spanning partitions
    fails fast here, via an allgathered digest cross-check of the
    original ids.  Group columns may be strings or arbitrary int64
    (the reference accepts StringType): ids are factorized host-side
    to dense per-shard codes, allgathered as integers, and offset to
    be globally unique before feeding the sharded query-pinned packing
    (ranking.shard_queries_from_shards).
    """

    is_rank = objective == "lambdarank"
    if bool(group_col) != is_rank:
        raise ValueError(
            "ranking configuration mismatch: objective='lambdarank' "
            "requires group_col, and group_col requires "
            "objective='lambdarank' (got objective="
            f"{objective!r}, group_col={group_col!r})")
    if ranking and not group_col:
        raise ValueError("ranking={...} without group_col has no effect; "
                         "pass the query/group column")

    def fn(task_index: int, batches: Iterable) -> Iterator:
        import jax
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_tasks,
                                   process_id=task_index)
        import pandas as pd
        from jax.experimental import multihost_utils
        from jax.sharding import Mesh

        from .core.mesh import DATA_AXIS, FEATURE_AXIS
        from .gbdt.engine import train
        from .gbdt.objectives import get_objective

        frames = [pd.DataFrame(b) if not isinstance(b, pd.DataFrame)
                  else b for b in batches]
        pdf = (pd.concat(frames, ignore_index=True) if frames else None)
        if pdf is None or len(pdf) == 0:
            # empty partition (skewed repartition): this task contributes
            # a zero-row shard — it must still reach the rendezvous and
            # allgathers below, or the other barrier tasks hang
            X = np.zeros((0, mapper.num_features), np.float64)
            y_local = np.zeros(0, np.float64)
            w_local = np.zeros(0, np.float64)
            q_local = np.zeros(0, np.int32)
            qdig_local = np.zeros(0, np.uint64)
        else:
            first = pdf[feature_col].iloc[0]
            X = (np.stack([np.asarray(v, np.float64)
                           for v in pdf[feature_col]])
                 if isinstance(first, (list, tuple, np.ndarray))
                 else pdf[[feature_col]].to_numpy(np.float64))
            y_local = pdf[label_col].to_numpy(np.float64)
            w_local = (pdf[weight_col].to_numpy(np.float64)
                       if weight_col else np.ones(len(y_local)))
            if group_col:
                # Factorize query ids to dense codes BEFORE the float
                # allgather: string ids (the reference's LightGBMRanker
                # accepts StringType) would raise under to_numpy(float64),
                # and int64 ids above 2**53 would silently merge/split
                # queries in float64 (ADVICE r4).  Queries are pinned to
                # their shard (group-contiguous partitions), so per-shard
                # dense codes group rows exactly.
                codes, uniq_q = pd.factorize(pdf[group_col])
                q_local = codes.astype(np.int32)
                # 64-bit digests of this shard's ORIGINAL ids: per-shard
                # dense codes can no longer collide across shards, so
                # the engine's query-spans-shards guard would go blind —
                # these digests are allgathered below to keep the
                # fail-fast on non-group-contiguous ingestion
                qdig_local = np.asarray(
                    [_qid_digest(v) for v in uniq_q], np.uint64)
            else:
                q_local = np.zeros(0, np.int32)
                qdig_local = np.zeros(0, np.uint64)
        bins_local = mapper.transform_packed(X)

        # global per-shard sizes + 1-D label/weight(/qid) metadata: pad
        # to the global max and allgather (process_allgather stacks
        # per-process host values), then slice back per shard
        sizes = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(y_local)]))).reshape(-1)
        S = int(sizes.max())
        pad = S - len(y_local)
        yw = np.stack([np.pad(y_local, (0, pad)),
                       np.pad(w_local, (0, pad))])
        yw_all = np.asarray(multihost_utils.process_allgather(yw))
        label_shards = [yw_all[d, 0, :sizes[d]] for d in range(num_tasks)]
        weight_shards = [yw_all[d, 1, :sizes[d]] for d in range(num_tasks)]
        ranking_info = None
        if group_col:
            # qids ride their OWN int32 allgather: the float gather above
            # goes through jax with x64 disabled, which would downcast
            # float64 to float32 and merge distinct large ids (ADVICE
            # r4).  Per-shard dense codes are then made globally unique
            # with a running base computed identically on every host —
            # the engine's query-spans-shards guard compares values
            # across shards.
            q_all = np.asarray(multihost_utils.process_allgather(
                np.pad(q_local, (0, pad), constant_values=-1)))
            qid_shards, base = [], 0
            for d in range(num_tasks):
                qd = q_all[d, :sizes[d]].astype(np.int64)
                qid_shards.append((qd + base).astype(np.float64))
                base += int(qd.max()) + 1 if len(qd) else 0
            # Re-arm the engine's query-spans-shards guard on ORIGINAL
            # ids: per-shard factorized codes are globally unique by
            # construction, so without this digest cross-check a query
            # split across partitions would silently train as two
            # queries instead of failing fast.
            nq = np.asarray(multihost_utils.process_allgather(
                np.asarray([len(qdig_local)], np.int32))).reshape(-1)
            dig = np.stack([(qdig_local >> np.uint64(32)).astype(np.uint32),
                            qdig_local.astype(np.uint32)])
            dig = np.pad(dig, ((0, 0), (0, int(nq.max()) - len(qdig_local))))
            dig_all = np.asarray(multihost_utils.process_allgather(dig))
            owner: dict = {}
            for d in range(num_tasks):
                for hi, lo in zip(dig_all[d, 0, :nq[d]],
                                  dig_all[d, 1, :nq[d]]):
                    key = (int(hi), int(lo))
                    if key in owner and owner[key] != d:
                        h64 = (int(hi) << 32) | int(lo)
                        local = ([str(v) for v in uniq_q
                                  if _qid_digest(v) == h64]
                                 if len(q_local) else [])
                        name = local[0] if local else f"digest {h64:#x}"
                        raise ValueError(
                            f"query {name} spans shards {owner[key]} and "
                            f"{d}: sharded lambdarank requires every "
                            f"query's rows on ONE shard (group-contiguous "
                            f"ingestion)")
                    owner[key] = d
            ranking_info = {
                "query_ids": qid_shards,
                "sigma": float((ranking or {}).get("sigma", 1.0)),
                "truncation_level": int(
                    (ranking or {}).get("truncation_level", 30)),
            }

        devs = np.asarray(jax.devices())
        if len(devs) != num_tasks:
            raise ValueError(
                f"executor_train_fn builds a data-only mesh with one "
                f"shard per barrier task: {num_tasks} tasks need exactly "
                f"{num_tasks} global devices, found {len(devs)} (set one "
                f"accelerator per task, or repartition to the device "
                f"count)")
        mesh = Mesh(devs.reshape(len(devs), 1), (DATA_AXIS, FEATURE_AXIS))
        slots = [None] * num_tasks
        slots[task_index] = bins_local
        booster = train(slots, label_shards, weight_shards, mapper,
                        get_objective(objective), params, mesh=mesh,
                        shard_rows=[int(s) for s in sizes],
                        ranking_info=ranking_info)
        if task_index == 0:
            yield pd.DataFrame(
                {"model": [booster.save_native_model_string()]})

    return fn


def score_udf(stage, result_cols: Optional[List[str]] = None,
              passthrough_cols: Optional[List[str]] = None
              ) -> Callable[[Iterable], Iterator]:
    """Executor-side scoring closure with the ``mapInPandas`` contract:
    ``Iterator[pandas.DataFrame] -> Iterator[pandas.DataFrame]``.

    Each executor deserializes the (broadcast-pickled) fitted stage once,
    then streams batches through ``stage.transform`` on its local jax
    backend — the analog of the reference's per-executor JNI scoring UDF,
    minus the per-row JNI calls.  Vector-valued outputs (probability,
    SHAP) flatten to list columns so they fit a Spark ``array<double>``
    schema.

    Works with any fitted mmlspark_tpu Transformer/Model; also directly
    callable on an iterator of pandas frames for Spark-free testing.
    """

    def fn(batches: Iterable) -> Iterator:
        import pandas as pd
        from .core.schema import to_table
        for pdf in batches:
            out = stage.transform(pdf)
            if not isinstance(out, pd.DataFrame):
                out = to_table(out).toPandas()
            cols = list(out.columns)
            if result_cols is not None or passthrough_cols is not None:
                keep = (passthrough_cols or []) + (result_cols or [])
                missing = [c for c in keep if c not in cols]
                if missing:
                    # fail fast on the driver-visible first batch — a
                    # schema mismatch otherwise surfaces as an opaque
                    # Arrow serializer error on the executors
                    raise KeyError(
                        f"score_udf: requested columns {missing} not in "
                        f"transform output; available: {cols}")
                cols = [c for c in cols if c in keep]
            yield out[cols]

    return fn
