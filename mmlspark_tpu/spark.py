"""Spark interop — the deployment-shape adapter (SURVEY.md §7 step 7).

The reference IS a Spark library; this framework replaces its execution
engine but keeps the Spark deployment story available: drive
mmlspark_tpu stages from a PySpark session, with executors running the
jitted compute against their local accelerator.  Nothing here imports
pyspark at module load — every entry point degrades cleanly when Spark
is absent (the common case for pure-TPU deployments), and the
``mapInPandas``-shaped scoring closure is a plain iterator-of-pandas
contract, so the executor-side path is testable without a JVM.

Pattern::

    from mmlspark_tpu.spark import from_spark, score_udf, to_spark

    table = from_spark(spark_df)               # driver: Arrow -> columns
    model = LightGBMClassifier(...).fit(table) # TPU training
    scored = spark_df.mapInPandas(             # executors: batched score
        score_udf(model, result_cols=["probability", "prediction"]),
        schema="...")

Reference analog: the generated PySpark wrappers + JNI scoring UDFs
(codegen/PySparkWrapper.scala, lightgbm scoring UDF; expected paths,
UNVERIFIED).
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, Optional

import numpy as np


def spark_available() -> bool:
    try:
        import pyspark  # noqa: F401
        return True
    except ImportError:
        return False


def from_spark(df, columns: Optional[List[str]] = None):
    """PySpark DataFrame → a pandas table our estimators consume.

    Uses Arrow-backed ``toPandas`` (enable
    ``spark.sql.execution.arrow.pyspark.enabled`` for zero-copy
    collection).  ``columns`` optionally projects before collecting —
    always project: the driver materializes what you collect.
    """
    if not (hasattr(df, "toPandas") and hasattr(df, "select")):
        raise TypeError(
            f"from_spark expects a PySpark DataFrame (got {type(df)!r})")
    if columns is not None:
        df = df.select(*columns)
    return df.toPandas()


def to_spark(table, spark):
    """Pandas/dict table → PySpark DataFrame via ``createDataFrame``.

    Vector columns become plain Python lists (``tolist``): numpy cells
    break Spark's non-Arrow row-type inference."""
    import pandas as pd
    from .core.schema import to_table
    if not isinstance(table, pd.DataFrame):
        table = to_table(table).toPandas()
    table = table.copy()
    for c in table.columns:
        first = table[c].iloc[0] if len(table) else None
        if isinstance(first, np.ndarray):
            table[c] = [np.asarray(v).tolist() for v in table[c]]
    return spark.createDataFrame(table)


def score_udf(stage, result_cols: Optional[List[str]] = None,
              passthrough_cols: Optional[List[str]] = None
              ) -> Callable[[Iterable], Iterator]:
    """Executor-side scoring closure with the ``mapInPandas`` contract:
    ``Iterator[pandas.DataFrame] -> Iterator[pandas.DataFrame]``.

    Each executor deserializes the (broadcast-pickled) fitted stage once,
    then streams batches through ``stage.transform`` on its local jax
    backend — the analog of the reference's per-executor JNI scoring UDF,
    minus the per-row JNI calls.  Vector-valued outputs (probability,
    SHAP) flatten to list columns so they fit a Spark ``array<double>``
    schema.

    Works with any fitted mmlspark_tpu Transformer/Model; also directly
    callable on an iterator of pandas frames for Spark-free testing.
    """

    def fn(batches: Iterable) -> Iterator:
        import pandas as pd
        from .core.schema import to_table
        for pdf in batches:
            out = stage.transform(pdf)
            if not isinstance(out, pd.DataFrame):
                out = to_table(out).toPandas()
            cols = list(out.columns)
            if result_cols is not None or passthrough_cols is not None:
                keep = (passthrough_cols or []) + (result_cols or [])
                missing = [c for c in keep if c not in cols]
                if missing:
                    # fail fast on the driver-visible first batch — a
                    # schema mismatch otherwise surfaces as an opaque
                    # Arrow serializer error on the executors
                    raise KeyError(
                        f"score_udf: requested columns {missing} not in "
                        f"transform output; available: {cols}")
                cols = [c for c in cols if c in keep]
            yield out[cols]

    return fn
