import numpy as np, jax, jax.numpy as jnp, time
from mmlspark_tpu.ops.histogram import compute_histogram
B, n, f = 256, 400000, 50
rng = np.random.default_rng(1)
bins = jnp.asarray(rng.integers(0, B, size=(n, f)), jnp.int32)
gh = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)

def bench(tag, fn, iters=10):
    r = fn(bins, gh); s = np.asarray(r).sum()   # warm + sync
    t0 = time.perf_counter()
    _ = np.asarray(fn(bins, gh)).sum()
    base = time.perf_counter() - t0             # 1 iter + fetch
    t0 = time.perf_counter()
    for _ in range(iters): r = fn(bins, gh)
    _ = np.asarray(r).sum()
    tot = time.perf_counter() - t0              # N iters + fetch
    per = (tot - base) / (iters - 1)
    print(f"{tag}: {per*1e3:.2f} ms/iter (1it+fetch={base*1e3:.0f}ms)")

for m in ("dot16", "pallas", "pallas_bf16"):
    bench(m, jax.jit(lambda b, g, mm=m: compute_histogram(b, g, B, method=mm)))
for rc in (32768, 131072):
    bench(f"dot16 rc={rc}", jax.jit(lambda b, g, r=rc: compute_histogram(b, g, B, method="dot16", row_chunk=r)))
