import jax, jax.numpy as jnp, numpy as np, functools
from jax.experimental import pallas as pl

def k1(x_ref, o_ref):
    x = x_ref[...]                       # (256, 8, 16)
    o_ref[...] = x.reshape(256, 128)     # collapse (8,16) -> 128 lanes

def k2(x_ref, o_ref):
    x = x_ref[...]                       # (8, 256)
    o_ref[...] = x.T                     # 2D transpose

def k3(x_ref, o_ref):
    x = x_ref[...]                       # (256, 128)
    o_ref[...] = jnp.repeat(x, 3, axis=1)  # lane-repeat 128->384

for name, kern, inshape, outshape in [
    ("reshape-collapse", k1, (256, 8, 16), (256, 128)),
    ("transpose2d", k2, (8, 256), (256, 8)),
    ("repeat3", k3, (256, 128), (256, 384)),
]:
    x = jnp.asarray(np.random.default_rng(0).normal(size=inshape), jnp.float32)
    try:
        out = pl.pallas_call(kern, out_shape=jax.ShapeDtypeStruct(outshape, jnp.float32))(x)
        ref = {"reshape-collapse": lambda: np.asarray(x).reshape(outshape),
               "transpose2d": lambda: np.asarray(x).T,
               "repeat3": lambda: np.repeat(np.asarray(x), 3, axis=1)}[name]()
        print(name, "OK maxdiff", float(np.max(np.abs(np.asarray(out) - ref))))
    except Exception as e:
        print(name, "FAIL:", str(e).split("\n")[0][:120])
