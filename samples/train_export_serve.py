"""End-to-end walkthrough: train -> export -> independent verify -> serve.

The reference's quickstart story (train a LightGBMClassifier, save the
native model, score it elsewhere, stand it up behind Spark Serving) on the
TPU-native stack.  Runs on any jax backend; pass ``--cpu`` to force the
CPU backend (some images pin ``JAX_PLATFORMS`` at interpreter startup,
so the env var alone may not stick).

    python samples/train_export_serve.py [--cpu]
"""

import json
import os
import sys
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    if "--cpu" in sys.argv[1:]:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from mmlspark_tpu.gbdt import (LightGBMClassificationModel,
                                   LightGBMClassifier)

    # ------------------------------------------------------------------ 1
    # Train on a synthetic adult-income-shaped table
    rng = np.random.default_rng(7)
    n = 20_000
    X = rng.normal(size=(n, 16)).astype(np.float32)
    y = ((X[:, 0] + 0.6 * X[:, 1] * X[:, 2] + np.sin(X[:, 3])
          + rng.normal(size=n) * 0.5) > 0).astype(np.float64)
    table = {"features": X, "label": y}

    model = LightGBMClassifier(
        numIterations=50, numLeaves=31, learningRate=0.1,
        verbosity=0).fit(table)
    from sklearn.metrics import roc_auc_score
    proba = np.asarray(model.transform(table)["probability"])[:, 1]
    print(f"[1] trained: train AUC = {roc_auc_score(y, proba):.4f}")

    # ------------------------------------------------------------------ 2
    # Export to the stock-LightGBM text format and reload
    path = "/tmp/mmlspark_tpu_sample_model.txt"
    model.saveNativeModel(path)
    print(f"[2] exported LightGBM v3 text model -> {path} "
          f"({os.path.getsize(path)} bytes)")

    # ------------------------------------------------------------------ 3
    # Independent verification: score a few rows with the spec-following
    # reference walker from the golden-interop test suite (no framework
    # code on that path) and compare to the framework's predictions.
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tests"))
    from test_golden_interop import _reference_predict
    reloaded = LightGBMClassificationModel.loadNativeModelFromFile(path)
    sample = X[:64]
    margins = np.asarray(reloaded.getModel().predict_margin(sample)).ravel()
    ours = 1.0 / (1.0 + np.exp(-margins))      # walker emits probabilities
    independent = _reference_predict(open(path).read(), sample)
    np.testing.assert_allclose(ours, independent, rtol=1e-5, atol=1e-6)
    print(f"[3] independent walker agrees on {len(sample)} rows "
          f"(max |diff| = {np.max(np.abs(ours - independent)):.2e})")

    # ------------------------------------------------------------------ 4
    # Serve it: HTTP in, batched model transform, HTTP out
    import threading

    from mmlspark_tpu.io.serving import HTTPServer, serve_forever

    server = HTTPServer(port=0).start()
    stop = threading.Event()

    def transform(t):
        feats = np.asarray(t["features"], np.float32)   # (rows, 16)
        out = reloaded.transform({"features": feats})
        return t.withColumn("reply", np.asarray([
            {"probability": float(p[1])}
            for p in np.asarray(out["probability"])], dtype=object))

    worker = threading.Thread(
        target=serve_forever,
        args=(server, transform, "reply"),
        kwargs={"max_rows": 32, "stop_event": stop}, daemon=True)
    worker.start()

    req = json.dumps({"features": X[0].tolist()}).encode()
    resp = urllib.request.urlopen(urllib.request.Request(
        f"http://{server.host}:{server.port}/", data=req,
        headers={"Content-Type": "application/json"}), timeout=10)
    answer = json.loads(resp.read())
    stop.set()
    server.stop()
    expect = float(proba[0])
    assert abs(answer["probability"] - expect) < 1e-5
    print(f"[4] served: POST -> probability {answer['probability']:.4f} "
          f"(matches batch transform {expect:.4f})")
    print("sample complete.")


if __name__ == "__main__":
    main()
