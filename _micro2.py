import time, numpy as np
import cProfile, pstats
rng = np.random.default_rng(0)
n, f = 20000, 20
X = rng.normal(size=(n, f)).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float64)
from mmlspark_tpu.gbdt import LightGBMClassifier
kw = dict(learningRate=0.1, numLeaves=31, maxBin=255, minDataInLeaf=20, verbosity=0)
LightGBMClassifier(numIterations=2, **kw).fit({"features": X, "label": y})
t0 = time.perf_counter()
pr = cProfile.Profile(); pr.enable()
LightGBMClassifier(numIterations=5, **kw).fit({"features": X, "label": y})
pr.disable()
print(f"fit: {time.perf_counter()-t0:.2f}s")
pstats.Stats(pr).sort_stats("cumulative").print_stats(18)
